"""A parameter server whose traffic rides the RPC framework as tensors.

This closes the loop SURVEY.md §2.11/§7 charters: the reference's headline
deployment is parameter-server fan-out over its RDMA transport; here the
served state is jax.Arrays in device memory, and every pull/push crosses
the framework's ``tpu://`` transport as a by-reference TensorArena
attachment (brpc_tpu/runtime/tensor.py):

  PULL:  device param --D2H--> server arena --by-ref--> client maps the
         same pages --jax.device_put--> device replica
  PUSH:  device grad --D2H--> client arena --by-ref--> server applies the
         fused Pallas momentum update ON DEVICE and bumps the version.

Reference mapping: example/parallel_echo_c++ fan-out + rdma payload path
(rdma_endpoint.h:89); the update rule matches ops/fused_update.py so a
local training loop and an RPC-driven one converge identically (asserted
by tests/test_tensor_bridge.py).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import weakref
from typing import Dict, List, Optional

import jax
import numpy as np

from brpc_tpu.ops.fused_update import fused_momentum_update
from brpc_tpu.runtime import codec as codec_mod
from brpc_tpu.runtime import groupwire, native
from brpc_tpu.runtime.tensor import (E_UNDECODABLE, OnesideGone, OnesideMiss,
                                     OnesideReader, OnesideWindow,
                                     PipelineWindow, TensorArena,
                                     TensorChannel, WireTensor,
                                     _dequant_widen,
                                     _detach_device_put_batch,
                                     _device_put_from_view,
                                     add_tensor_service,
                                     consume_oneside_payload, pad_header64)

# App-level error codes, disjoint from trpc/errno.h. The server
# historically answered "no such parameter" with 2007 — which COLLIDES
# with TRPC_ECONNECT, so a fleet client couldn't tell "that shard doesn't
# have it" (don't retry) from "that shard is unreachable" (do retry):
# E_NO_SUCH moves to its own code. E_MOVED's text carries the forwarding
# address as "moved:<host:port>" — the fleet client parses it to re-route
# mid-reshard; E_MIGRATING means installed-but-uncommitted (retry soon).
E_NO_SUCH = 2040
E_MOVED = 2041
E_MIGRATING = 2042
E_EXISTS = 2043  # install over a live (serving) parameter
# E_UNDECODABLE = 2044 lives in tensor.py (the raise site is the typed-send
# trampoline); it completes this 2040+ app-code range.

# trpc/errno.h transport code a handler bug surfaces as — what a PRE-codec
# server answers to a quantized push (see _codec_push_failed).
TRPC_EINTERNAL = 2004

_MOVED_RE = re.compile(r"moved:(\S+)")


def moved_dest(err: "native.RpcError") -> Optional[str]:
    """The forwarding address an E_MOVED redirect carries, or None."""
    if err.code != E_MOVED:
        return None
    m = _MOVED_RE.search(err.text or "")
    return m.group(1) if m else None


class OverloadPacer:
    """Client-side brake for shed storms (the retry-after consumer).

    ELIMIT/EOVERCROWDED answers mean the server (or the socket write
    queue) is over capacity RIGHT NOW — hot-retrying turns one shed into
    a storm that keeps the server pinned at its admission gate. The shed
    response carries a drain-time hint (" (retry_after_ms=N)", from the
    server's EMA latency); this pacer holds the NEXT call back until the
    hint elapses, doubling an exponential floor when sheds repeat without
    a hint, and heals instantly on the first success. The same role the
    native per-node CircuitBreaker (trpc/circuit_breaker.h) plays for
    transport failures, at the application layer where overload answers
    live (an ELIMIT response IS a received response, so the transport
    breaker rightly never trips on it).

    Thread-safe; `sheds` is the bounded-retry-rate observable the
    shed-storm test asserts against the server's per-tenant counters."""

    _MIN_DELAY_S = 0.005
    _MAX_DELAY_S = 0.5

    def __init__(self):
        self._mu = threading.Lock()
        self._until = 0.0   # monotonic time before which calls pace
        self._delay = 0.0   # current backoff floor
        self.sheds = 0

    def note(self, err) -> float:
        """Record an error; returns the pacing delay now owed (0 for
        non-overload errors, which also leave the pacer untouched)."""
        if not getattr(err, "overloaded", False):
            return 0.0
        hint_s = (getattr(err, "retry_after_ms", None) or 0) / 1000.0
        with self._mu:
            self.sheds += 1
            self._delay = min(max(self._delay * 2, self._MIN_DELAY_S),
                              self._MAX_DELAY_S)
            delay = max(hint_s, self._delay)
            self._until = max(self._until, time.monotonic() + delay)
            return max(0.0, self._until - time.monotonic())

    def clear(self) -> None:
        """A success: the server is admitting again — stop pacing."""
        with self._mu:
            self._delay = 0.0
            self._until = 0.0

    def pace(self) -> None:
        """Sleep out any pacing debt before issuing the next call.
        Client-side only: runs on the CALLER's thread (training loop /
        fleet worker), never inside a server handler."""
        with self._mu:
            wait = self._until - time.monotonic()
        if wait > 0:
            time.sleep(wait)  # tpulint: allow(py-blocking)


class PartialPullError(native.RpcError):
    """A ``pull_all`` that delivered SOME tensors before a per-name
    failure: ``partial`` holds the decoded ``{name: (version, value)}``,
    ``missing`` the names not delivered (the failed name plus anything
    the aborted window never drained). Raised instead of discarding the
    survivors so the fleet's salvage path re-routes ONLY the stragglers
    — mid-reshard, one moved tensor must not cost its groupmates a
    second full group RPC. Catches as a plain RpcError (same code/text
    as the first failure) for callers that don't care."""

    def __init__(self, cause: "native.RpcError",
                 partial: Dict[str, tuple], missing: List[str]):
        super().__init__(cause.code, cause.text)
        self.partial = partial
        self.missing = missing


class PartialPushError(native.RpcError):
    """A ``push_all`` that APPLIED some gradients before a per-name
    failure: ``applied`` holds the confirmed ``{name: new_version}``,
    ``unpushed`` the names with no confirmed apply (the failed name plus
    anything the aborted window never drained — those MAY have landed
    server-side with the reply lost, the usual retry ambiguity). Raised
    instead of discarding the confirmed versions: re-pushing a gradient
    the server already applied is not idempotent (a second momentum step
    and version bump corrupt training state), so the fleet's salvage
    path must re-route ONLY the unconfirmed names. Catches as a plain
    RpcError (same code/text as the first failure) for callers that
    don't care."""

    def __init__(self, cause: "native.RpcError",
                 applied: Dict[str, int], unpushed: List[str]):
        super().__init__(cause.code, cause.text)
        self.applied = applied
        self.unpushed = unpushed


# Process-wide recorders (brpc_tpu/observability): every ParameterServer
# instance feeds the same series, like native per-method stats aggregate.
_metrics_cache = None
_SERVERS: "weakref.WeakSet[ParameterServer]" = weakref.WeakSet()


def _max_version_lag() -> int:
    """Largest (max - min) parameter-version spread across live servers —
    how far the most- and least-updated parameters have drifted apart.
    Reads the lock-free mirror each Push maintains: gauge callbacks run
    at scrape time under the native registry walk, so taking srv._mu here
    would stall every metrics consumer behind an in-flight update."""
    return max((srv._version_spread for srv in list(_SERVERS)), default=0)


def _metrics():
    global _metrics_cache
    if _metrics_cache is None:
        from brpc_tpu.observability import metrics as obs

        _metrics_cache = {
            # HANDLER-BODY time only: Pull's D2H + arena staging happens
            # after the handler returns (add_tensor_service trampoline) —
            # the tensor_handler recorder carries that full server-side
            # cost; the client's tensor_pull carries the end-to-end view.
            "pull": obs.latency("param_server_pull"),
            # PullQ groups up to _GROUP tensors per sample — a separate
            # recorder, or quant traffic would read as ~8x slower/rarer
            # pulls beside the per-tensor path.
            "pull_group": obs.latency("param_server_pull_group"),
            "push": obs.latency("param_server_push"),
            # PushQ applies up to _GROUP updates per sample — its own
            # recorder for the same reason pull_group has one.
            "push_group": obs.latency("param_server_push_group"),
            "push_bytes": obs.counter("param_server_push_bytes"),
            "lag": obs.gauge("param_server_version_lag", _max_version_lag),
        }
    return _metrics_cache


def _per_server_lag_gauge(name: str, srv: "ParameterServer") -> None:
    """Expose this server's version spread as its OWN gauge
    (`param_server_version_lag_<name>`) beside the process-wide max —
    satellite: per-server (and per-shard, via the fleet's shard names)
    version-lag series on /vars, /brpc_metrics and /tensorz. Re-pointable
    (newest server claiming the name wins) and weakly bound, so a test's
    re-created server neither collides nor leaks."""
    from brpc_tpu.observability import metrics as obs

    safe = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    ref = weakref.ref(srv)
    # `safe` is re.sub-sanitized to the exposition charset just above.
    obs.repointable_gauge(
        f"param_server_version_lag_{safe}",  # tpulint: allow(metric-name)
        lambda: getattr(ref(), "_version_spread", 0))


class ParameterServer:
    """Serves named jax.Arrays over RPC; Push applies momentum SGD.

    Shard-aware (brpc_tpu/fleet): Meta carries a schema epoch (bumped when
    the parameter SET changes — Install/Retire — never by plain updates,
    so clients can cache the name->shape/dtype map); Handoff/Install/
    Retire/Commit are the live-resharding handshake a fleet Migrator
    drives. Per-name migration states:

      serving  normal pulls + pushes
      frozen   Handoff exported it: pulls still served (old-owner reads
               until the handoff commits), pushes refused with E_MOVED so
               no update can land that the export missed
      pending  Installed here but not yet committed: pulls served (same
               version the old owner still serves), pushes refused with
               E_MIGRATING until Commit — so a version can never advance
               on the new owner while the old owner still answers reads

    A retired name answers E_MOVED with "moved:<dest>" so clients holding
    a stale shard map re-route without a registry round trip.
    """

    def __init__(self, params: Dict[str, jax.Array], lr: float = 0.01,
                 momentum: float = 0.9, arena: Optional[TensorArena] = None,
                 name: Optional[str] = None, codecs=None,
                 oneside: bool = False,
                 oneside_codec: Optional[str] = None):
        # Backend split for the Push hot path. On TPU the update is the
        # fused Pallas kernel over device arrays (device_put = a real H2D
        # DMA). On the CPU backend that same shape is all dispatch
        # overhead: per-push jax dispatch (~0.5ms) dominated the pipelined
        # bench, and device_put ZERO-COPY ALIASES 64B-aligned host buffers
        # — with the update dispatched async, the grad view's arena range
        # could be reused under the pending computation. The CPU path
        # keeps params/momenta as numpy and applies the update
        # synchronously, reading straight from the request view (safe:
        # the read completes before the handler returns and the view
        # releases) — but COPY-ON-WRITE, never in place; see
        # _apply_update for why handed-out arrays must stay immutable.
        self._on_device = jax.default_backend() == "tpu"
        if self._on_device:
            self._params = dict(params)
            self._momenta = {k: jax.numpy.zeros_like(v)
                             for k, v in self._params.items()}
        else:
            self._params = {k: np.array(v) for k, v in params.items()}
            self._momenta = {k: np.zeros_like(v)
                             for k, v in self._params.items()}
        self._version = {k: 0 for k in self._params}
        self._lr = lr
        self._momentum = momentum
        # Per-parameter update locks: pushes to the SAME name must
        # serialize (momentum reads its own previous write), but pushes to
        # different names are independent — and numpy releases the GIL for
        # the 1MB elementwise math, so pipelined pushes of a sharded model
        # really do update in parallel. _mu stays the dict/version lock
        # and is never held while an update lock is taken... the update
        # lock is taken FIRST (fixed order, no cycle).
        self._update_locks = {k: threading.Lock() for k in self._params}
        # Update admission: a pipelined client parks a whole window of
        # pushes on the server at once, and running every update's math
        # concurrently just thrashes the cores the transport needs (the
        # math releases the GIL, so an unbounded pool really does fan
        # out). Cap concurrent update computations near the core count;
        # excess handlers queue on the semaphore (pool pthreads — safe to
        # block) with the wire already overlapped.
        self._update_sem = threading.BoundedSemaphore(
            min(4, max(2, os.cpu_count() or 2)))
        self._mu = threading.Lock()  # handlers run on callback-pool threads
        # Lock-free mirror of max(version)-min(version), updated by Push
        # under _mu, read by the version-lag gauge without it.
        self._version_spread = 0
        # ---- shard-aware state (brpc_tpu/fleet) ----
        # Schema epoch: bumps when the parameter SET changes (Install /
        # Retire), never on plain version bumps — the client Meta cache key.
        self._schema_epoch = 1
        self._state: Dict[str, str] = {}        # absent == "serving"
        self._handoff_dest: Dict[str, str] = {}  # frozen name -> dest addr
        self._moved: Dict[str, str] = {}         # retired name -> dest addr
        # ---- quantized tensor wire (brpc_tpu/runtime/codec.py) ----
        # Codecs this server will encode pulls with / decode pushes from,
        # advertised in Meta (the per-peer negotiation); codecs=() turns
        # the feature off entirely (every call rides raw).
        # Advertising a codec this build cannot decode (e.g. fp8e4m3
        # without ml_dtypes) would let a client negotiate pushes the
        # server then cannot parse — intersect, caller order kept.
        self._codecs = tuple(codec_mod.supported_codecs() if codecs is None
                             else (c for c in codecs
                                   if c in codec_mod.supported_codecs()))
        # Quantize-once-serve-many: Pull responses are encoded per
        # (version, codec) and cached until the next update replaces them
        # — name -> {codec: (version, meta, wire uint8 array, logical)}.
        # Holds ~1/4 of the fp32 parameter bytes per codec in use when
        # clients pull quantized; invalidation pops the whole name.
        self._enc_cache: Dict[str, Dict[str, tuple]] = {}
        self.name = name
        if name is not None:
            _per_server_lag_gauge(name, self)
        _SERVERS.add(self)
        self._m = _metrics()
        self.server = native.Server()
        self.arena = add_tensor_service(self.server, "ParamService",
                                        self._handle, arena)
        # ---- one-sided tensor reads (brpc_tpu/runtime/tensor.py) ----
        # Publish every committed version into a seqlock-stamped window of
        # the service arena: a same-host client that mapped the window
        # pulls WITHOUT an RPC (no dispatch, no handler, no response
        # frame), falling back to the Pull path off-host. Published
        # regions may hold the encoded wire form (oneside_codec) — the
        # reader decodes by the same self-describing header the RPC path
        # ships, so the two paths cannot disagree.
        self._oneside_window: Optional[OnesideWindow] = None
        self._oneside_codec = (oneside_codec
                               if oneside_codec in self._codecs else None)
        if oneside:
            self._oneside_window = OnesideWindow(self.arena)
            for k in list(self._params):
                with self._update_locks[k]:
                    self._publish_oneside(k)
        self.port: Optional[int] = None

    def start(self, addr: str = "127.0.0.1:0") -> int:
        self.port = self.server.start(addr)
        return self.port

    def stop(self) -> None:
        self.server.stop()

    # ---- handler (runs inside a server fiber) ----
    def _handle(self, method: str, request: bytes, att):
        from brpc_tpu.observability import tracing

        if method == "Meta":
            # Under _mu: Push swaps self._params values and bumps
            # self._version concurrently on other fibers — an unlocked
            # read here can pair a new version with an old shape/dtype
            # (or hit a dict mutated mid-iteration).
            with self._mu:
                meta = {}
                for k, v in self._params.items():
                    entry = {"shape": list(v.shape), "dtype": str(v.dtype),
                             "version": self._version[k]}
                    state = self._state.get(k)
                    if state is not None:  # frozen/pending: the migrator's
                        entry["state"] = state  # repair pass reads this
                    meta[k] = entry
                epoch = self._schema_epoch
            # "qos": 1 is the QoS-advertisement half of the negotiation
            # discipline (same pattern as "codecs"): clients stamp
            # priority/tenant wire fields ONLY after seeing it, so an
            # upgraded client never sends a meta a pre-QoS parser would
            # reject.
            # "pushq": grouped quantized pushes served here (the PullQ
            # write-side twin) — advertised like the codec so a client
            # never sends a method an older build lacks.
            doc = {"epoch": epoch, "params": meta, "qos": 1,
                   "codecs": list(self._codecs), "pushq": 1}
            # One-sided advertisement (the codec/QoS negotiation
            # discipline): clients ask for the window descriptor only
            # after seeing it, so a pre-oneside server never receives an
            # Oneside method call it cannot parse.
            if self._oneside_window is not None:
                doc["oneside"] = 1
            return json.dumps(doc).encode(), None
        if method == "Epoch":
            # The Meta-cache validator: a tiny small-RPC-fast-path answer
            # (schema epoch only) instead of the full Meta payload.
            with self._mu:
                epoch = self._schema_epoch
            return json.dumps({"epoch": epoch}).encode(), None
        if method == "PullQ":
            return self._handle_pull_group(request)
        if method == "PushQ":
            return self._handle_push_group(request, att, tracing)
        if method == "Oneside":
            # The mapping handshake: ONE ordinary RPC hands out the
            # window descriptor; every read after it is memory-semantics.
            if self._oneside_window is None:
                raise native.RpcError(E_NO_SUCH, "one-sided reads disabled")
            desc = self._oneside_window.describe()
            # The token stays a decimal STRING on the wire (the capi
            # contract): a double-typed JSON parser would round a bare
            # u64 above 2^53 and the reader's token check would fail
            # forever. OnesideReader.map int()s either form.
            desc["token"] = str(desc["token"])
            return json.dumps(desc).encode(), None
        if method == "Handoff":
            return self._handle_handoff(request)
        if method == "Install":
            return self._handle_install(request, att)
        if method == "Retire":
            return self._handle_retire(request)
        if method == "Commit":
            return self._handle_commit(request)
        # Per-call codec negotiation marker: "<name>\x00<codec>" — only
        # sent by clients that saw this codec in our Meta advertisement,
        # so a plain name (every pre-codec client) parses unchanged.
        name_b, _, want_b = request.partition(b"\x00")
        name = name_b.decode()
        want = want_b.decode()
        with self._mu:
            known = name in self._params
            dest = self._moved.get(name)
        if not known:
            if dest is not None:
                raise native.RpcError(E_MOVED,
                                      f"parameter {name} moved:{dest}")
            raise native.RpcError(E_NO_SUCH, f"no such parameter: {name}")
        if method == "Pull":
            t0 = time.monotonic()
            with self._mu:
                if name not in self._params:  # retired under our feet
                    moved = self._moved.get(name)
                    if moved is not None:
                        raise native.RpcError(
                            E_MOVED, f"parameter {name} moved:{moved}")
                    raise native.RpcError(E_NO_SUCH,
                                          f"no such parameter: {name}")
                p = self._params[name]
                version = self._version[name]
            out = str(version).encode(), self._encode_pull(name, p, version,
                                                           want)
            self._m["pull"].record_s(time.monotonic() - t0)
            return out
        if method == "Push":
            if att is None:
                raise native.RpcError(native.TRPC_EREQUEST,
                                      "push without gradient")
            t0 = time.monotonic()
            self._update_sem.acquire()
            try:
                version = self._apply_update(name, att, tracing)
            finally:
                self._update_sem.release()
            self._m["push"].record_s(time.monotonic() - t0)
            self._m["push_bytes"].add(att.nbytes)
            return str(version).encode(), None
        raise native.RpcError(E_NO_SUCH, f"no such method: {method}")

    # ---- quantized pull encode (quantize once, serve many) ----

    def _encoded_entry(self, name: str, p, version: int, want: str):
        """-> (meta_dict, flat uint8 wire bytes) for one pull response
        tensor: the block-quantized codes when the caller negotiated a
        codec this server enables AND the tensor is eligible (fp32, above
        the size floor), else the raw bytes (meta carries no codec key —
        the per-call degrade). Quantized entries are encoded once per
        (version, codec) and cached PER CODEC until the next update
        replaces them — mixed int8/fp8 clients each get their own slot
        instead of thrashing one (a parameter server serves many more
        pulls than it takes pushes, so the quantize cost amortizes to
        ~zero; the 4x-smaller response staging is pure win)."""
        # eligible() reads dtype/nbytes only — an ineligible tensor from
        # a negotiated client skips straight to raw with NO host
        # materialization here (the trampoline's place() does the one
        # D2H the response needs).
        if want and want in self._codecs and codec_mod.eligible(p):
            with self._mu:
                ent = self._enc_cache.get(name, {}).get(want)
            if ent is None or ent[0] != version:
                host = np.asarray(p)  # one D2H on the device path
                enc = codec_mod.encode(host, want)
                if enc is None:
                    ent = None  # defensive: eligible() said yes above
                else:
                    meta = {"dtype": host.dtype.str,
                            "shape": list(host.shape),
                            "codec": want, "block": enc.block}
                    ent = (version, meta, enc.wire, int(host.nbytes))
                    with self._mu:
                        # Re-check under _mu: a concurrent Retire may
                        # have popped the name (params AND cache) while
                        # we encoded lock-free — inserting now would
                        # strand the wire bytes until a re-install.
                        # Still SERVE this response (the snapshot `p`
                        # predates the retire, matching single-Pull
                        # semantics); just don't cache it.
                        if name in self._params:
                            self._enc_cache.setdefault(name, {})[want] = ent
            if ent is not None:
                codec_mod.note(name, want, ent[3], int(ent[2].nbytes))
                return ent[1], ent[2]
        host = np.asarray(p)
        return ({"dtype": host.dtype.str, "shape": list(host.shape)},
                np.ascontiguousarray(host).reshape(-1).view(np.uint8))

    def _encode_pull(self, name: str, p, version: int, want: str):
        """The single-Pull response tensor: the array itself (raw — the
        trampoline stages it with the legacy header, byte-identical to
        the pre-codec wire) or the cached quantized bytes as a
        WireTensor."""
        if (not want or want not in self._codecs
                or not codec_mod.eligible(p)):
            return p  # raw: the trampoline's place() is the only D2H
        meta, data = self._encoded_entry(name, p, version, want)
        if "codec" not in meta:
            return p  # ineligible: identical to the never-negotiated path
        return WireTensor(data, codec_mod.pack_header(meta))

    def _handle_pull_group(self, request: bytes):
        """PullQ: one RPC carrying MANY pull responses — the quantized
        wire's second lever. Once the codec cuts a 1MB tensor to ~0.26MB,
        the per-RPC fixed cost (dispatch, handler hop, response staging
        bookkeeping) dominates a per-tensor pull stream, so the client
        groups pulls and this handler concatenates the encoded tensors
        into ONE attachment behind a JSON manifest. (Raw pulls stay
        per-tensor: at 4 logical bytes per wire byte they are transport-
        bound, and grouping would buy nothing — measured in PERF r9.)

        Per-name misses ride the manifest as {"name", "code", "error"}
        entries instead of failing the group: mid-reshard a single moved
        tensor must not poison its groupmates; the client re-routes the
        stragglers through the per-tensor retry path.
        """
        t0 = time.monotonic()
        req = json.loads(request.decode())
        want = req.get("codec", "")
        entries, blobs, total = [], [], 0
        for name in req["names"]:
            with self._mu:
                known = name in self._params
                moved = self._moved.get(name)
                if known:
                    p = self._params[name]
                    version = self._version[name]
            if not known:
                entries.append({
                    "name": name,
                    "code": E_MOVED if moved else E_NO_SUCH,
                    "error": (f"parameter {name} moved:{moved}" if moved
                              else f"no such parameter: {name}")})
                continue
            meta, data = self._encoded_entry(name, p, version, want)
            e = dict(meta)
            e["name"] = name
            e["version"] = version
            e["nbytes"] = int(data.nbytes)
            entries.append(e)
            blobs.append(data)
            total += int(data.nbytes)
        # Write each encoded tensor straight into the service arena (the
        # writes ARE the staging transfer) and hand the trampoline the
        # pre-placed range — a concat buffer here would be memcpy'd into
        # the arena AGAIN by place(), one redundant full-payload copy per
        # group on the hot quantized pull path.
        placed = (0, 0)  # all-miss group: manifest only, no attachment
        if total:
            arena_off = self.arena.alloc(total)
            try:
                view = self.arena.view(arena_off, total)
                off = 0
                for b in blobs:
                    view[off:off + b.nbytes] = b.reshape(-1)
                    off += b.nbytes
            except BaseException:
                self.arena.free(arena_off)
                raise
            placed = (arena_off, total)
        self._m["pull_group"].record_s(time.monotonic() - t0)
        return (json.dumps({"tensors": entries}).encode(),
                WireTensor(None, b"", placed=placed))

    def _handle_push_group(self, request: bytes, att, tracing):
        """PushQ: one RPC carrying MANY gradient pushes — the write-side
        twin of PullQ (PR 7's named leftover, retired here). The client
        concatenates its quantized gradients behind a groupwire manifest;
        this handler slices the attachment per entry and applies each
        update exactly like a per-tensor Push would (same QuantizedView
        decode, same per-name update locks and admission semaphore, same
        version bumps), answering a manifest of per-name results.

        Per-name salvage is the whole point: a moved/undecodable name
        answers ``{"name", "code", "error"}`` in the results instead of
        failing its groupmates — re-pushing an APPLIED gradient is not
        idempotent (double momentum step), so a client must learn
        exactly which names landed.
        """
        t0 = time.monotonic()
        man = groupwire.parse_group(request)
        payload = None
        if att is not None:
            payload = np.ascontiguousarray(att).reshape(-1).view(np.uint8)
        try:
            pairs = list(groupwire.split_group(man, payload))
        except ValueError as ve:
            raise native.RpcError(E_UNDECODABLE,
                                  f"undecodable push group: {ve}")
        results = []
        for entry, run in pairs:
            name = entry.get("name", "?")
            try:
                if "codec" in entry:
                    grad = codec_mod.QuantizedView(entry, run)
                    logical = grad.nbytes
                else:
                    grad = run.view(np.dtype(entry["dtype"])).reshape(
                        tuple(entry["shape"]))
                    logical = int(grad.nbytes)
                self._update_sem.acquire()
                try:
                    version = self._apply_update(name, grad, tracing)
                finally:
                    self._update_sem.release()
                self._m["push_bytes"].add(logical)
                results.append({"name": name, "version": version})
            except native.RpcError as e:
                results.append({"name": name, "code": e.code,
                                "error": e.text})
            except ValueError as ve:
                # Corrupt entry (size mismatch, unknown codec): the
                # E_UNDECODABLE discipline, per name — groupmates after
                # it still apply.
                results.append({
                    "name": name, "code": E_UNDECODABLE,
                    "error": f"undecodable tensor payload for {name}: "
                             f"{ve}"})
        self._m["push_group"].record_s(time.monotonic() - t0)
        return json.dumps({"results": results}).encode(), None

    # ---- one-sided publication (memory-semantics pulls) ----

    def _publish_oneside(self, name: str) -> None:
        """Publish ``name``'s committed version into the one-sided
        window: [self-describing header|bytes] — raw, or the encoded
        wire form when ``oneside_codec`` engages — written into a fresh
        arena range the window takes ownership of (the displaced
        version's range retires through epoch reclamation, never under a
        reader mid-copy). Callers hold the per-name update lock, so
        publish order matches version order. Arena exhaustion skips the
        publish — readers of this name fall back to the RPC path, which
        serves the same committed state."""
        win = self._oneside_window
        if win is None:
            return
        with self._mu:
            if name not in self._params:
                return
            p = self._params[name]
            version = self._version[name]
        host = np.asarray(p)  # one D2H on the device path
        header = data = None
        c = self._oneside_codec
        if c and codec_mod.eligible(host):
            enc = codec_mod.encode(host, c)
            if enc is not None:
                header, data = enc.header, enc.wire
                codec_mod.note(name, c, enc.logical_bytes, enc.wire_bytes)
        if data is None:
            header = codec_mod.pack_header({"dtype": host.dtype.str,
                                            "shape": list(host.shape)})
            data = np.ascontiguousarray(host).reshape(-1).view(np.uint8)
        # 64B-multiple header => the payload starts 64B-aligned in the
        # blob, so a reader's device_put can alias it zero-copy.
        header = pad_header64(header)
        total = len(header) + int(data.nbytes)
        try:
            off = self.arena.alloc(total)
        except MemoryError:
            return  # unpublished version: one-sided readers fall back
        view = self.arena.view(off, total)
        view[:len(header)] = np.frombuffer(header, dtype=np.uint8)
        if data.nbytes:
            view[len(header):] = data.reshape(-1)
        try:
            win.publish(name, off, total, version)
        except (ValueError, RuntimeError):
            self.arena.free(off)

    # ---- live-resharding handshake (driven by brpc_tpu/fleet.Migrator) ----

    def _recompute_spread_locked(self) -> None:
        vs = self._version.values()
        self._version_spread = max(vs) - min(vs) if vs else 0

    def _handle_handoff(self, request: bytes):
        """Freeze `name` for export: pushes refuse with E_MOVED from here
        on (no update can land that the export would miss); pulls keep
        serving the frozen — latest committed — version until Retire.
        Returns {"version"} + the stacked [param, momentum] tensor.
        Idempotent: a migrator retry re-exports the same frozen state."""
        req = json.loads(request.decode())
        name, dest = req["name"], req.get("dest", "")
        with self._mu:
            lock = self._update_locks.get(name)
            if lock is None:
                moved = self._moved.get(name)
                if moved is not None:
                    raise native.RpcError(E_MOVED,
                                          f"parameter {name} moved:{moved}")
                raise native.RpcError(E_NO_SUCH,
                                      f"no such parameter: {name}")
        with lock:  # an in-flight push completes (or sees frozen) first
            with self._mu:
                if name not in self._params:  # retired while we waited
                    moved = self._moved.get(name)
                    raise native.RpcError(
                        E_MOVED, f"parameter {name} retired"
                        + (f"; moved:{moved}" if moved else ""))
                self._state[name] = "frozen"
                if dest:
                    self._handoff_dest[name] = dest
                p = self._params[name]
                m = self._momenta[name]
                version = self._version[name]
        # Updates are functional (p/m replaced, never mutated) and frozen
        # names take no more of them: stacking outside the locks reads
        # stable arrays. One D2H per array on the device path.
        stacked = np.stack([np.asarray(p), np.asarray(m)])
        return json.dumps({"name": name, "version": version}).encode(), stacked

    def _handle_install(self, request: bytes, att):
        """Adopt a handed-off tensor in `pending` state: pulls serve it
        (same version the frozen old owner still answers), pushes refuse
        with E_MIGRATING until Commit — a version can never advance here
        while the old owner still serves reads. Idempotent re-install of a
        pending name is allowed (migrator retry)."""
        req = json.loads(request.decode())
        name = req["name"]
        version = int(req.get("version", 0))
        if att is None:
            raise native.RpcError(native.TRPC_EREQUEST,
                                  "install without tensor payload")
        if att.ndim < 1 or att.shape[0] != 2:
            raise native.RpcError(
                native.TRPC_EREQUEST,
                f"install expects stacked [param, momentum], "
                f"got shape {tuple(att.shape)}")
        # Detach from the sender's arena pages BEFORE the handler returns.
        param = np.array(att[0])
        mom = np.array(att[1])
        if self._on_device:
            param = _device_put_from_view(param, None)
            mom = _device_put_from_view(mom, None)
        with self._mu:
            # Re-install over `pending` (migrator retry) or `frozen` (this
            # shard handed the name off once and a later remap brought it
            # back before the stale copy was retired) is recovery, not a
            # conflict; only a SERVING copy refuses.
            if name in self._params and self._state.get(name) not in (
                    "pending", "frozen"):
                raise native.RpcError(
                    E_EXISTS, f"install over live parameter: {name}")
            self._params[name] = param
            self._momenta[name] = mom
            self._version[name] = version
            self._enc_cache.pop(name, None)  # encoded for the old bytes
            self._update_locks.setdefault(name, threading.Lock())
            self._state[name] = "pending"
            self._moved.pop(name, None)  # keys can migrate back later
            self._handoff_dest.pop(name, None)  # any old freeze is void
            self._schema_epoch += 1
            self._recompute_spread_locked()
        # Pending names refuse pushes until Commit, so no concurrent
        # publish can race this one out of version order.
        self._publish_oneside(name)
        return json.dumps({"name": name, "version": version}).encode(), None

    def _handle_retire(self, request: bytes):
        """Drop a handed-off tensor and remember its forwarding address:
        later pulls/pushes answer E_MOVED "moved:<dest>" so stale-mapped
        clients re-route without a registry round trip. Idempotent."""
        req = json.loads(request.decode())
        name, dest = req["name"], req.get("dest", "")
        with self._mu:
            lock = self._update_locks.get(name)
        if lock is not None:
            with lock:
                with self._mu:
                    self._params.pop(name, None)
                    self._momenta.pop(name, None)
                    self._version.pop(name, None)
                    self._enc_cache.pop(name, None)
                    self._update_locks.pop(name, None)
                    self._state.pop(name, None)
                    self._handoff_dest.pop(name, None)
                    if dest:  # an empty dest would forward into "moved:"
                        self._moved[name] = dest  # — unparseable; a plain
                    self._schema_epoch += 1       # drop answers E_NO_SUCH
                    self._recompute_spread_locked()
                if self._oneside_window is not None:
                    # A retired name must not serve stale one-sided reads:
                    # mapped clients miss here and re-route via E_MOVED.
                    self._oneside_window.unpublish(name)
        else:
            with self._mu:
                if dest and self._moved.get(name) != dest:
                    # Recording a (new) redirect is a schema change too —
                    # without the bump a warm Meta cache on this server
                    # would keep validating against the pre-retire set.
                    self._moved[name] = dest
                    self._schema_epoch += 1
        return json.dumps({"name": name}).encode(), None

    def _handle_commit(self, request: bytes):
        """pending -> serving: the write-side commit point. Ordered by the
        Migrator AFTER the old owner retired, so reads and writes can
        never disagree across the two owners."""
        name = request.decode()
        with self._mu:
            if name not in self._params:
                moved = self._moved.get(name)
                if moved is not None:
                    raise native.RpcError(E_MOVED,
                                          f"parameter {name} moved:{moved}")
                raise native.RpcError(E_NO_SUCH,
                                      f"no such parameter: {name}")
            self._state.pop(name, None)
            # A stale forwarding hint must not outlive the commit: a later
            # dest-less Handoff would re-surface it as a dead redirect.
            self._handoff_dest.pop(name, None)
        return b"ok", None

    def _apply_update(self, name: str, att, tracing) -> int:
        if isinstance(att, codec_mod.QuantizedView):
            # Quantized gradient push: account the wire win, then either
            # dequantize on-device (H2D moves the ~4x smaller codes, the
            # Pallas/jnp kernel widens there) or into a fresh host buffer
            # (which IS the detach the CPU path needs anyway).
            codec_mod.note(name, att.codec, att.nbytes, att.wire_nbytes)
            if self._on_device:
                with tracing.stage("device_put"):
                    q_dev, s_dev = _detach_device_put_batch(
                        [(att.q, att.scales)], None)
                with tracing.stage("dequant"):
                    grad = _dequant_widen(q_dev, s_dev, att.block, att.n,
                                          att.shape)
            else:
                with tracing.stage("dequant"):
                    att = att.dequantize()
        elif self._on_device:
            with tracing.stage("device_put"):
                # H2D DMA from the request view, completed (and thus
                # detached from the arena pages) before the handler
                # returns and the view's range can be reused.
                grad = _device_put_from_view(np.ascontiguousarray(att), None)
        with self._mu:
            lock = self._update_locks.get(name)
            if lock is None:  # retired between the known-check and here
                moved = self._moved.get(name)
                raise native.RpcError(
                    E_MOVED, f"parameter {name} retired"
                    + (f"; moved:{moved}" if moved else ""))
        with lock:
            with self._mu:
                if name not in self._params:  # retired while we waited
                    moved = self._moved.get(name)
                    raise native.RpcError(
                        E_MOVED, f"parameter {name} retired"
                        + (f"; moved:{moved}" if moved else ""))
                state = self._state.get(name)
                if state == "frozen":
                    dest = self._handoff_dest.get(name)
                    raise native.RpcError(
                        E_MOVED, f"parameter {name} handed off"
                        + (f"; moved:{dest}" if dest else ""))
                if state == "pending":
                    raise native.RpcError(
                        E_MIGRATING,
                        f"parameter {name} migrating in; retry shortly")
                p = self._params[name]
                m = self._momenta[name]
            with tracing.stage("fused_update"):
                if self._on_device:
                    # Dispatch-only: blocking on device completion here
                    # would serialize every update behind its device
                    # round-trip; JAX's async dispatch already orders
                    # later reads of the new arrays.
                    p2, m2 = fused_momentum_update(
                        p, m, grad.astype(p.dtype),
                        lr=self._lr, beta=self._momentum)
                else:
                    # Copy-on-write numpy momentum step, read straight
                    # from the zero-copy view. NOT in-place: a Pull's
                    # response staging copies the returned array after
                    # the handler drops _mu, so arrays must stay
                    # immutable once handed out (same discipline as the
                    # jax path's functional update).
                    g = att.astype(p.dtype, copy=False)
                    m2 = self._momentum * m + g
                    p2 = p - self._lr * m2
            with self._mu:
                self._params[name] = p2
                self._momenta[name] = m2
                self._version[name] += 1
                version = self._version[name]
                self._recompute_spread_locked()
            # Inside the per-name update lock: publish order == version
            # order, so a mapped reader's versions are monotonic.
            self._publish_oneside(name)
        return version


class ParameterClient:
    """Pulls params into device arrays / pushes device grads, all over the
    framework (one TensorChannel per client).

    ``codec="int8"`` (or ``"fp8e4m3"``) asks for the quantized tensor
    wire format (brpc_tpu/runtime/codec.py): engaged per call only after
    the server advertises the codec in Meta — against an older or
    codec-disabled server everything rides raw, transparently. Pulls
    request quantized responses; pushes quantize gradients with
    error-feedback accumulators (the residual of push k rides along with
    push k+1, so repeated pushes never compound rounding bias)."""

    def __init__(self, addr: str, arena: Optional[TensorArena] = None,
                 codec: Optional[str] = None, tenant: str = "",
                 oneside: bool = False):
        self.addr = addr
        self.channel = TensorChannel(addr, arena)
        # Meta cache keyed by the server's schema epoch: the epoch bumps
        # only when the parameter SET changes (Install/Retire), so the
        # name -> shape/dtype map stays valid across ordinary pushes.
        # Cached VERSIONS are stale by design — versions ride each pull.
        self._meta_epoch: Optional[int] = None
        self._meta_cache: Optional[dict] = None
        self._codec = codec
        self._srv_codecs: Optional[tuple] = None  # unknown until Meta
        # PushQ advertisement (grouped quantized pushes): False until the
        # server's Meta carried "pushq": 1 — a PR 7-era server decodes
        # quantized per-tensor pushes but has no PushQ method, so the
        # method itself is negotiated separately from the codec.
        self._srv_pushq = False
        self._ef = codec_mod.ErrorFeedback()
        # Overload protection: the tenant id this client's requests carry
        # (the server's per-tenant quota key; "" falls back to peer ip
        # server-side), and the shed-storm pacer overload answers feed.
        self._tenant = tenant
        self.pacer = OverloadPacer()
        # QoS negotiation state: None until the first Meta fetch; True
        # when the server advertised "qos": 1. Stamping before the
        # advertisement (or against a pre-QoS server, whose parser
        # rejects the unknown meta fields) would kill the connection.
        self._srv_qos: Optional[bool] = None
        # One-sided reads: engaged only when asked for AND the server
        # advertises "oneside" in Meta AND its window maps (same host).
        # _oneside_reader: None = not tried yet, False = permanently on
        # the RPC path (off-host / disabled / gone), else the mapping.
        self._oneside = oneside
        self._oneside_reader = None
        self._srv_oneside: Optional[bool] = None

    # ---- QoS lanes (native/trpc/qos.h) ----
    # Control-plane calls (Epoch, the migrator handshake) ride HIGH —
    # they must stay live while bulk tensor traffic saturates the
    # server's gate; Pull/Push/PullQ ride BULK and accept the headroom
    # shed. NEGOTIATED like the codec advertisement: fields are stamped
    # only after the server's Meta carried "qos": 1 (a pre-QoS parser
    # reads the extra meta bytes as a corrupt service name and kills the
    # connection), and Meta itself — the negotiation vehicle — always
    # rides unstamped so renegotiation works against any build.

    def _qos(self, priority: int):
        import contextlib

        if self._srv_qos is None:
            # Lazy negotiation (the codec pattern): one Meta RPC the
            # first time a stamped call would happen. A fetch failure
            # leaves the state unknown — this call rides unstamped and a
            # later one retries the advertisement.
            try:
                self.meta()
            except Exception:  # noqa: BLE001 — the op itself will report
                pass
        if not self._srv_qos:
            return contextlib.nullcontext()
        return native.qos(priority, self._tenant)

    def _qos_high(self):
        return self._qos(native.PRIORITY_HIGH)

    def _qos_bulk(self):
        return self._qos(native.PRIORITY_BULK)

    def _qos_failed(self, e: "native.RpcError") -> bool:
        """Self-heal a stale QoS advertisement: a server rolled back to a
        pre-QoS build rejects stamped frames at PARSE time, which
        surfaces client-side as a transport error (connection killed —
        EEOF/EFAILEDSOCKET/ECONNECT). Re-read the advertisement ONCE
        (Meta rides unstamped, so it works against any build); True =
        the server no longer advertises QoS and the caller should retry
        its now-unstamped call. Genuine transport failures re-advertise
        and keep their error, costing one Meta RPC on an already-failing
        path — the _codec_pull_failed discipline."""
        if not self._srv_qos or e.code not in native.TRANSPORT_DEAD:
            return False
        self._srv_qos = None
        try:
            self.meta()
        except Exception:  # noqa: BLE001 — keep the original error
            return False
        return not self._srv_qos

    def meta(self) -> dict:
        # UNSTAMPED deliberately: Meta is the negotiation vehicle for both
        # the codec and the QoS advertisement — it must parse on any
        # build, including one that predates the QoS meta fields.
        payload, _ = self.channel.call("ParamService/Meta")
        doc = json.loads(payload.decode())
        self._meta_epoch = doc["epoch"]
        self._meta_cache = doc["params"]
        self._srv_codecs = tuple(doc.get("codecs", ()))
        self._srv_qos = bool(doc.get("qos", 0))
        self._srv_oneside = bool(doc.get("oneside", 0))
        self._srv_pushq = bool(doc.get("pushq", 0))
        return doc["params"]

    def epoch(self) -> int:
        """The server's schema epoch (a tiny small-RPC-fast-path call)."""
        with self._qos_high():
            payload, _ = self.channel.call("ParamService/Epoch")
        return json.loads(payload.decode())["epoch"]

    def cached_meta(self) -> dict:
        """The Meta map through the epoch-validated cache: one Epoch
        round trip (bytes, not the whole schema) when warm; a full Meta
        fetch only on the first call or an epoch mismatch."""
        if self._meta_cache is not None and self.epoch() == self._meta_epoch:
            return self._meta_cache
        return self.meta()

    # ---- per-call codec negotiation (quantized tensor wire) ----

    def negotiated_codec(self) -> Optional[str]:
        """The codec this client/server pair agreed on, or None (raw).
        The advertisement is fetched on first use (one Meta RPC) and
        then trusted for the client's lifetime — NOT revalidated per
        call (this runs per pull/push). Pulls from any codec-aware
        server are safe regardless of restarts (decode follows the
        response's self-describing header); the stale-advertisement
        failure modes all self-heal: a push the server can no longer
        decode answers E_UNDECODABLE (_codec_push_failed drops the
        advertisement), a push to a PRE-codec rollback dies
        TRPC_EINTERNAL (_codec_push_failed re-reads the advertisement
        and heals only when the codec is gone), and a pull a PRE-codec
        rollback reads as an unknown name/method dies E_NO_SUCH
        (_codec_pull_failed re-reads the advertisement and retries raw
        when it changed)."""
        if self._codec is None:
            return None
        if self._srv_codecs is None:
            # Full Meta fetch, NOT cached_meta(): after an invalidation
            # the schema epoch usually still matches (restarted servers
            # reuse epochs), and the epoch-hit path returns the cached
            # map without repopulating the advertisement — renegotiation
            # must actually re-read it.
            self.meta()
        return codec_mod.choose(self._codec, self._srv_codecs)

    def _codec_push_failed(self, e: "native.RpcError") -> None:
        """Self-heal a stale codec advertisement: a server restarted
        without our negotiated codec (build lost ml_dtypes, operator
        set codecs=()) cannot decode our quantized pushes."""
        if e.code == E_UNDECODABLE:
            self._srv_codecs = None  # renegotiate on the next call
            return
        if e.code != TRPC_EINTERNAL or self.negotiated_codec() is None:
            return
        # A PRE-codec build has no E_UNDECODABLE answer: its trampoline
        # hands the handler the flat quantized bytes, whose shape
        # mismatch dies in the update math as a generic internal error.
        # Mirror _codec_pull_failed: re-read the advertisement ONCE — a
        # rollback no longer carries our codec (heal; the next push
        # rides raw), while a genuine handler bug re-advertises the same
        # codec and keeps both its error and the negotiation, costing
        # one Meta RPC on an already-failing path.
        self._srv_codecs = None
        try:
            self.meta()
        except Exception:  # noqa: BLE001 — keep the original error
            pass

    def _pushq_failed(self, e: "native.RpcError") -> bool:
        """A grouped push that died E_NO_SUCH may mean the server rolled
        back to a pre-PushQ build (PR 7-era: quantized per-tensor pushes
        fine, no PushQ method) — per-NAME misses ride the result
        manifest, so a group-level E_NO_SUCH is the method itself.
        Re-read the advertisement once (the _codec_pull_failed
        discipline); True = PushQ is gone and the caller should retry
        per-tensor (still quantized if the codec survives)."""
        if e.code != E_NO_SUCH or not self._srv_pushq:
            return False
        self._srv_codecs = None  # force a FULL Meta re-read (see
        try:                     # negotiated_codec on epoch reuse)
            self.meta()
        except Exception:  # noqa: BLE001 — keep the original error
            return False
        return not self._srv_pushq

    def _codec_pull_failed(self, e: "native.RpcError") -> bool:
        """A NEGOTIATED pull that died E_NO_SUCH may mean the server was
        rolled back to a pre-codec build: such a server reads the
        "name\\x00codec" marker as part of an unknown parameter name, and
        has no PullQ method at all — every pull wedges as "no such"
        although raw would work. Re-read the advertisement ONCE: if it no
        longer carries our codec, renegotiation happened and the caller
        should retry (now raw). A genuine miss re-advertises the same
        codec, so misses cost one extra Meta RPC and keep their error —
        success paths pay nothing."""
        if e.code != E_NO_SUCH or self.negotiated_codec() is None:
            return False
        self._srv_codecs = None
        try:
            self.meta()
        except Exception:  # noqa: BLE001 — keep the original error
            return False
        return self.negotiated_codec() is None

    # ---- one-sided reads (memory-semantics pulls) ----

    def _oneside_enabled(self, oneside: Optional[bool]) -> bool:
        return self._oneside if oneside is None else bool(oneside)

    def _ensure_oneside_reader(self):
        """The mapped window, lazily established: one Meta RPC for the
        advertisement (the codec/QoS negotiation discipline), one
        Oneside RPC for the descriptor, one map. Any failure parks this
        client permanently on the RPC path — off-host mappings cannot
        start working later, and a restarted server re-advertises
        through a fresh client."""
        r = self._oneside_reader
        if r is not None:
            return r if r is not False else None
        if self._srv_oneside is None:
            try:
                self.meta()
            except native.RpcError:
                return None  # unknown stays unknown: retry next call
        if not self._srv_oneside:
            self._oneside_reader = False
            return None
        try:
            payload, _ = self.channel.call("ParamService/Oneside")
            desc = json.loads(payload.decode())
            r = OnesideReader.map(desc)
        except (native.RpcError, ValueError):
            r = None
        self._oneside_reader = r if r is not None else False
        return r

    def _drop_oneside_reader(self) -> None:
        r = self._oneside_reader
        self._oneside_reader = False  # permanent fallback
        if r not in (None, False):
            r.close()

    def _oneside_read(self, name: str, device=None, to_host: bool = False):
        """-> (version, array) straight from the peer's published window,
        or None when this pull should ride the RPC path (every miss
        counts into oneside_pull_fallbacks; the RPC path serves the same
        committed state, so fallback is invisible to the caller)."""
        from brpc_tpu.runtime.tensor import _metrics

        m = _metrics()
        r = self._ensure_oneside_reader()
        if r is None:
            m["oneside_fallbacks"].add(1)
            return None
        try:
            # read_np: the owned-ndarray form — one copy out of the
            # window, viewed (and on CPU device_put-aliased) in place.
            version, payload = r.read_np(name)
        except OnesideGone:
            self._drop_oneside_reader()
            m["oneside_fallbacks"].add(1)
            return None
        except OnesideMiss:
            m["oneside_fallbacks"].add(1)
            return None
        try:
            arr = consume_oneside_payload(payload, device, note_name=name,
                                          to_host=to_host)
        except Exception:  # noqa: BLE001 — undecodable publication
            m["oneside_fallbacks"].add(1)
            return None
        m["oneside_hits"].add(1)
        return int(version), arr

    def prune_residuals(self, keep) -> int:
        """Drop error-feedback residuals for names failing ``keep(name)``.
        Fleet reshard hook: once a name's ownership moves to another
        shard this client never pushes it again, and its residual (a
        full-gradient-sized fp32 buffer) would otherwise live for the
        client's lifetime. Dropping one costs at most a single quant
        step of accuracy on a stream that has already ended."""
        return self._ef.prune(keep)

    def _pull_request(self, name: str) -> bytes:
        """Pull request bytes: the bare name (byte-identical to the
        pre-codec wire) unless a codec is negotiated — then the per-call
        marker the server's Pull parses. Also used by the fleet's shard
        streams, so single-server and fleet negotiation cannot drift."""
        c = self.negotiated_codec()
        return name.encode() + (b"\x00" + c.encode() if c else b"")

    def _grad_encoder(self, name: str):
        """The per-tensor PipelineWindow/push_device encoder closure for
        a quantized gradient push (None when riding raw): compensates
        with the error-feedback residual, quantizes at arena-stage time,
        settles the new residual."""
        c = self.negotiated_codec()
        if c is None:
            # Raw stream: nothing will be owed, and a residual left by
            # an EARLIER quantized push (stream degraded after an
            # E_UNDECODABLE self-heal) is a full-gradient-sized fp32
            # buffer that would otherwise strand for the client's
            # lifetime. Dropping it costs at most one quant step on a
            # stream that has ended.
            self._ef.clear(name)
            return None

        def enc(host: np.ndarray):
            if not codec_mod.eligible(host):
                self._ef.clear(name)  # nothing quantized, nothing owed
                return None
            x = self._ef.compensate(name, host)
            e = codec_mod.encode(x, c)
            if e is None:
                self._ef.clear(name)
                return None
            self._ef.settle(name, x, e.dequantized())
            codec_mod.note(name, c, e.logical_bytes, e.wire_bytes)
            return e.wire, e.header

        return enc

    def pull(self, name: str, device=None, oneside: Optional[bool] = None):
        """-> (version, jax.Array) — H2D straight from the shared pages.

        ``oneside=True`` (or the constructor flag) reads the committed
        version straight from the server's published window when it is
        mapped — no RPC at all — and falls back here transparently
        otherwise."""
        if self._oneside_enabled(oneside):
            got = self._oneside_read(name, device)
            if got is not None:
                return got
        self.pacer.pace()
        try:
            with self._qos_bulk():
                rest, arr = self.channel.pull_device(
                    "ParamService/Pull", request=self._pull_request(name),
                    device=device, note_name=name)
        except native.RpcError as e:
            self.pacer.note(e)
            if not (self._codec_pull_failed(e) or self._qos_failed(e)):
                raise
            # Renegotiated (server rolled back to a pre-codec or pre-QoS
            # build): the retried request is byte-identical to the wire
            # that build speaks.
            with self._qos_bulk():
                rest, arr = self.channel.pull_device(
                    "ParamService/Pull", request=self._pull_request(name),
                    device=device)
        self.pacer.clear()
        return int(rest.decode()), arr

    def push_grad(self, name: str, grad) -> int:
        """Send a device gradient; returns the server's new version."""
        self.pacer.pace()
        try:
            with self._qos_bulk():
                payload = self.channel.push_device(
                    "ParamService/Push", grad, request=name.encode(),
                    encoder=self._grad_encoder(name))
        except native.RpcError as e:
            self.pacer.note(e)
            self._codec_push_failed(e)
            if self._qos_failed(e):
                # Pre-QoS rollback: retry once unstamped (the heal
                # re-read the advertisement; the frame is now the old
                # wire exactly).
                payload = self.channel.push_device(
                    "ParamService/Push", grad, request=name.encode(),
                    encoder=self._grad_encoder(name))
            else:
                raise
        self.pacer.clear()
        return int(payload.decode())

    # ---- live-resharding handshake (used by brpc_tpu/fleet.Migrator) ----

    def handoff(self, name: str, dest: str = ""):
        """Freeze + export `name` -> (version, stacked [param, momentum]
        host array). The server refuses pushes to it from now on."""
        req = json.dumps({"name": name, "dest": dest}).encode()
        with self._qos_high():  # migrator handshake = control plane
            payload, stacked = self.channel.call("ParamService/Handoff",
                                                 request=req)
        return json.loads(payload.decode())["version"], stacked

    def install(self, name: str, stacked, version: int,
                commit: bool = False) -> None:
        """Adopt a stacked [param, momentum] tensor at `version` in
        pending state; `commit=True` also flips it serving (reseed path)."""
        req = json.dumps({"name": name, "version": int(version)}).encode()
        with self._qos_high():
            self.channel.call("ParamService/Install", array=stacked,
                              request=req)
        if commit:
            self.commit(name)

    def retire(self, name: str, dest: str = "") -> None:
        req = json.dumps({"name": name, "dest": dest}).encode()
        with self._qos_high():
            self.channel.call("ParamService/Retire", request=req)

    def commit(self, name: str) -> None:
        with self._qos_high():
            self.channel.call("ParamService/Commit", request=name.encode())

    # ---- pipelined multi-tensor hot path (PipelineWindow) ----
    # The serial pull/push above pay one full round-trip per tensor: a
    # model with N parameter tensors pays N x the ~260us 1MB latency
    # floor (PERF.md round 3) although the transport sustains ~3x the
    # single-stream throughput at conc=8 (BENCH r05). These keep a
    # bounded window of RPCs in flight instead, so N tensors cost ~1
    # round-trip plus N wire times.

    def pull_all(self, names=None, device=None, window: int = 4,
                 group: int = 8, to_host: bool = False,
                 oneside: Optional[bool] = None) -> Dict[str, tuple]:
        """Pull many parameters through one bounded pipeline window.

        -> ``{name: (version, jax.Array)}``. ``names=None`` pulls every
        parameter the server's Meta lists. ``to_host=True`` returns
        DETACHED host ndarrays instead of device arrays (the fleet's
        shard streams use this: device dispatch from N threads contends,
        so shards stop at host copies and the caller dispatches alone).

        Raw (no negotiated codec): one RPC per tensor, each
        ``jax.device_put`` straight from its zero-copy response view —
        byte-identical to the pre-codec wire. Negotiated codec: pulls ride
        ``PullQ`` in groups of ``group`` tensors per RPC — the codec cuts
        each tensor ~4x, which leaves the per-RPC fixed cost dominating a
        per-tensor stream, so grouping is where the second half of the
        effective-bandwidth win comes from (PERF round 9).
        """
        from brpc_tpu.runtime.tensor import (_decode_meta_ex, _metrics,
                                             _stage, consume_pull_reply)

        self.pacer.pace()  # shed-storm brake: honor any retry-after debt
        listed_meta = None
        if names is None:
            listed_meta = self.cached_meta()
            names = sorted(listed_meta)
        names = list(names)
        m = _metrics()
        out: Dict[str, tuple] = {}
        # One-sided pre-pass: every name the mapped window serves skips
        # the RPC plane entirely; the stragglers (unpublished, torn,
        # unmapped, off-host) ride the pipelined RPC path below — the
        # per-shard locality routing the fleet client inherits as-is.
        if self._oneside_enabled(oneside) and names:
            rest = []
            for n in names:
                got = self._oneside_read(n, device, to_host=to_host)
                if got is not None:
                    out[n] = got
                else:
                    rest.append(n)
            if not rest:
                return out
            names = rest
        c = self.negotiated_codec()

        if c is None:
            if to_host:
                def on_reply(name, payload, view):
                    with view:
                        meta, rest = _decode_meta_ex(payload)
                        host = np.array(np.frombuffer(
                            view.ndarray(),
                            dtype=np.dtype(meta["dtype"])).reshape(
                                tuple(meta["shape"])))
                    m["pull_bytes"].add(host.nbytes)
                    out[name] = (int(rest.decode()), host)
            else:
                def on_reply(name, payload, view):
                    rest, dev, nbytes = consume_pull_reply(payload, view,
                                                           device)
                    m["pull_bytes"].add(nbytes)
                    out[name] = (int(rest.decode()), dev)

            try:
                with self._qos_bulk(), PipelineWindow(
                        self.channel, window, on_reply=on_reply) as win:
                    for name in names:
                        win.submit("ParamService/Pull",
                                   request=self._pull_request(name),
                                   tag=name)
            except native.RpcError as e:
                self.pacer.note(e)
                if out:
                    raise PartialPullError(
                        e, dict(out),
                        [n for n in names if n not in out]) from e
                raise
            self.pacer.clear()
            return out

        import jax

        target = device if device is not None else jax.devices()[0]
        on_accel = getattr(target, "platform", "cpu") != "cpu"

        # Codec-ineligible tensors (non-fp32 / below the size floor) gain
        # nothing from PullQ — the server serves them raw inside the
        # group and the client's manifest decode costs a full host copy
        # the per-tensor path avoids (_device_put_from_view aliases the
        # response view). Meta already carries dtype/shape, so predict
        # eligibility and keep those names on the per-tensor raw path
        # (same window, so they still pipeline). Host-copy targets
        # (to_host) pay the copy either way — no reason to split.
        # Prediction misses (name absent from the cached map, or the
        # server swapped the tensor since) just ride the group, whose
        # raw-entry decode stays correct.
        singles: list = []
        if not to_host:
            try:
                meta_map = (listed_meta if listed_meta is not None
                            else self.cached_meta())
            except native.RpcError:
                meta_map = {}

            def _predict_eligible(n: str) -> bool:
                e = meta_map.get(n)
                if e is None:
                    return True  # unknown: the group reports it per-name
                return (e["dtype"] == "float32"
                        and int(np.prod(e["shape"], dtype=np.int64)) * 4
                        >= codec_mod.MIN_QUANT_BYTES)

            singles = [n for n in names if not _predict_eligible(n)]
        single_set = set(singles)
        grouped = ([n for n in names if n not in single_set]
                   if singles else names)

        def on_group(_tag, payload, view):
            # Decode every tensor of the group while the view is held
            # (the codes live in the peer's pages), then dispatch ONE
            # jax.device_put for the whole group: per-tensor dispatch is
            # ~0.1-0.4ms of pure overhead on this path (PR 6 measured the
            # contention flavor of the same cost), and the dequant output
            # is a FRESH buffer — no view-release hazard, so no per-
            # tensor block_until_ready either.
            metas, hosts = [], []
            qmetas, qpairs, qdevs = [], [], []
            err: Optional[native.RpcError] = None
            with view:
                man = json.loads(payload.decode())
                # b"" (not None): a group of only zero-size tensors ships
                # a manifest with no attachment, and b""[0:0] keeps the
                # slice-decode loop valid for their empty entries.
                buf = view.ndarray() if view.nbytes else b""
                off = 0
                for t in man["tensors"]:
                    if "error" in t:
                        # Surface like the per-tensor path would — after
                        # the groupmates decoded (a moved tensor must not
                        # poison them; the fleet retries it per name).
                        if err is None:
                            err = native.RpcError(t["code"], t["error"])
                        continue
                    nb = t["nbytes"]
                    sub = buf[off:off + nb]
                    off += nb
                    if "codec" in t:
                        # Decode side of the tensor_codec_* accounting:
                        # pull-only processes must still show their
                        # logical/wire bytes and ratio on /vars+/tensorz.
                        codec_mod.note(
                            t["name"], t["codec"],
                            int(np.prod(t["shape"], dtype=np.int64))
                            * np.dtype(t["dtype"]).itemsize, nb)
                    try:
                        with _stage("dequant"):
                            if on_accel and not to_host and "codec" in t:
                                # Real accelerator: collect the (4x
                                # smaller) codes+scales views; the single
                                # H2D below detaches the whole group.
                                q, s = codec_mod.split_wire(t, sub)
                                qmetas.append(t)
                                qpairs.append((q, s))
                                continue
                            if "codec" in t:
                                host = codec_mod.decode(t, sub)
                            else:
                                host = np.array(np.frombuffer(
                                    sub, dtype=np.dtype(t["dtype"])
                                ).reshape(tuple(t["shape"])))
                    except ValueError as ve:
                        # Corrupt entry: ride the same per-name error
                        # path as a manifest miss (groupmates survive
                        # into PartialPullError; a bare ValueError would
                        # bypass the salvage and the fleet re-route).
                        if err is None:
                            err = native.RpcError(
                                E_UNDECODABLE, "undecodable tensor "
                                f"payload for {t['name']}: {ve}")
                        continue
                    metas.append(t)
                    hosts.append(host)
                if qpairs:
                    with _stage("dequant"):
                        # Detach the whole group before the view releases
                        # (one put + one barrier — see the helper).
                        qdevs = _detach_device_put_batch(qpairs, device)
            if qmetas:
                with _stage("dequant"):
                    for i, t in enumerate(qmetas):
                        val = _dequant_widen(
                            qdevs[2 * i], qdevs[2 * i + 1], t["block"],
                            int(np.prod(t["shape"], dtype=np.int64)),
                            t["shape"], want=t["dtype"])
                        out[t["name"]] = (int(t["version"]), val)
                        m["pull_bytes"].add(
                            int(np.prod(t["shape"], dtype=np.int64))
                            * np.dtype(t["dtype"]).itemsize)
            if hosts:
                vals = hosts if to_host else jax.device_put(hosts, device)
                for t, val in zip(metas, vals):
                    m["pull_bytes"].add(
                        int(np.prod(t["shape"], dtype=np.int64))
                        * np.dtype(t["dtype"]).itemsize)
                    out[t["name"]] = (int(t["version"]), val)
            if err is not None:
                raise err

        def on_reply(tag, payload, view):
            if isinstance(tag, tuple):
                return on_group(tag, payload, view)
            # Predicted-ineligible per-tensor pull: raw reply, zero-copy
            # device_put straight from the view (the path the raw branch
            # above uses; the self-describing header keeps this correct
            # even if the server quantized after all).
            rest, dev, nbytes = consume_pull_reply(payload, view, device,
                                                   note_name=tag)
            m["pull_bytes"].add(nbytes)
            out[tag] = (int(rest.decode()), dev)

        try:
            with self._qos_bulk(), PipelineWindow(
                    self.channel, window, on_reply=on_reply) as win:
                for name in singles:
                    win.submit("ParamService/Pull",
                               request=self._pull_request(name), tag=name)
                for i in range(0, len(grouped), max(1, group)):
                    g = grouped[i:i + max(1, group)]
                    req = json.dumps({"names": g, "codec": c}).encode()
                    win.submit("ParamService/PullQ", request=req,
                               tag=tuple(g))
        except native.RpcError as e:
            self.pacer.note(e)
            if self._codec_pull_failed(e):
                # Pre-codec rollback (no PullQ method): renegotiated to
                # raw — re-pull the stragglers through the per-tensor
                # raw path and merge, keeping any decoded survivors.
                rem = [n for n in names if n not in out]
                try:
                    out.update(self.pull_all(rem, device=device,
                                             window=window, group=group,
                                             to_host=to_host))
                except PartialPullError as pe:
                    raise PartialPullError(pe, {**out, **pe.partial},
                                           pe.missing) from pe
                except native.RpcError as re2:
                    # The raw re-pull died before delivering anything new
                    # (e.g. the rolled-back server is still restarting).
                    # The survivors in `out` must still reach the caller.
                    if out:
                        raise PartialPullError(
                            re2, dict(out),
                            [n for n in rem if n not in out]) from re2
                    raise
                return out
            if out:
                raise PartialPullError(
                    e, dict(out),
                    [n for n in names if n not in out]) from e
            raise
        self.pacer.clear()
        return out

    def push_all(self, grads: Dict[str, object], window: int = 4,
                 group: int = 8) -> Dict[str, int]:
        """Push many gradients through one bounded pipeline window.

        -> ``{name: new_version}``. Staging (D2H + arena memcpy) of
        gradient k+1 overlaps the wire transfer of gradient k; the
        client arena never holds more than ``window`` staged gradients.

        Raw (no negotiated codec): one Push RPC per tensor —
        byte-identical to the pre-codec wire. Negotiated codec against a
        PushQ-advertising server: eligible gradients quantize (with
        error feedback) into groups of ``group`` per PushQ RPC — the
        codec cuts each ~4x, which leaves the per-RPC fixed cost
        dominating a per-tensor stream, the same second lever PullQ is
        on the read side (PERF round 9). Per-name results ride the
        response manifest; a moved/undecodable name raises
        :class:`PartialPushError` with its groupmates' confirmed
        versions in ``applied``.
        """
        from brpc_tpu.runtime.tensor import _as_host_array, _metrics
        m = _metrics()
        versions: Dict[str, int] = {}
        per_name_err: Dict[str, native.RpcError] = {}
        c = self.negotiated_codec()
        use_group = c is not None and self._srv_pushq and group > 1

        def on_reply(tag, payload, view):
            view.release()  # push responses carry no tensor
            if isinstance(tag, tuple):
                doc = json.loads(payload.decode())
                for r in doc["results"]:
                    if "error" in r:
                        per_name_err[r["name"]] = native.RpcError(
                            int(r["code"]), r["error"])
                    else:
                        versions[r["name"]] = int(r["version"])
            else:
                versions[tag] = int(payload.decode())

        self.pacer.pace()
        try:
            with self._qos_bulk(), PipelineWindow(
                    self.channel, window, on_reply=on_reply) as win:
                if not use_group:
                    for name, grad in grads.items():
                        win.submit("ParamService/Push", array=grad,
                                   request=name.encode(), tag=name,
                                   encoder=self._grad_encoder(name))
                        m["push_bytes"].add(
                            int(getattr(grad, "nbytes", 0)))
                else:
                    # Split by METADATA (dtype/nbytes — no D2H needed),
                    # then materialize host copies one group slice at a
                    # time: an up-front copy of every gradient would
                    # hold a full host replica of the model where the
                    # per-tensor path never stages more than `window`
                    # tensors. Ineligible tensors ride per-tensor raw
                    # in the SAME window so they still pipeline (submit
                    # does their D2H, window-bounded).
                    names = list(grads)

                    def _predict(g) -> bool:
                        try:
                            return (np.dtype(getattr(g, "dtype", None))
                                    == np.float32
                                    and int(getattr(g, "nbytes", 0))
                                    >= codec_mod.MIN_QUANT_BYTES)
                        except TypeError:
                            return False

                    grouped = [n for n in names if _predict(grads[n])]
                    gset = set(grouped)
                    for name in names:
                        if name in gset:
                            continue
                        self._ef.clear(name)  # raw hop: nothing owed
                        win.submit("ParamService/Push",
                                   array=grads[name],
                                   request=name.encode(), tag=name)
                        m["push_bytes"].add(
                            int(getattr(grads[name], "nbytes", 0)))
                    for i in range(0, len(grouped), group):
                        gnames = grouped[i:i + group]
                        entries, blobs = [], []
                        for n in gnames:
                            host = _as_host_array(grads[n])
                            x = self._ef.compensate(n, host)
                            e = codec_mod.encode(x, c)
                            if e is None:  # raced ineligible: raw
                                self._ef.clear(n)
                                win.submit("ParamService/Push",
                                           array=host,
                                           request=n.encode(), tag=n)
                                m["push_bytes"].add(host.nbytes)
                                continue
                            self._ef.settle(n, x, e.dequantized())
                            codec_mod.note(n, c, e.logical_bytes,
                                           e.wire_bytes)
                            entries.append(
                                {"name": n, "dtype": host.dtype.str,
                                 "shape": list(host.shape),
                                 "codec": c, "block": e.block})
                            blobs.append(e.wire)
                            m["push_bytes"].add(host.nbytes)
                        if entries:
                            manifest, concat = groupwire.pack_group(
                                entries, blobs)
                            win.submit("ParamService/PushQ",
                                       array=concat, request=manifest,
                                       tag=tuple(e["name"]
                                                 for e in entries))
        except native.RpcError as e:
            self.pacer.note(e)
            self._codec_push_failed(e)
            group_tagged = isinstance(getattr(e, "pipeline_tag", None),
                                      tuple)
            if group_tagged and self._pushq_failed(e):
                # Pre-PushQ rollback: the method is gone, the names are
                # fine — re-push the unconfirmed stragglers per-tensor
                # (renegotiated; still quantized if the codec survived)
                # and merge, keeping every confirmed version.
                rem = {n: grads[n] for n in grads if n not in versions}
                try:
                    versions.update(self.push_all(rem, window=window,
                                                  group=group))
                except PartialPushError as pe:
                    raise PartialPushError(
                        pe, {**versions, **pe.applied},
                        pe.unpushed) from pe
                except native.RpcError as re2:
                    if versions:
                        raise PartialPushError(
                            re2, dict(versions),
                            [n for n in rem if n not in versions]
                        ) from re2
                    raise
                return versions
            if versions:
                raise PartialPushError(
                    e, dict(versions),
                    [n for n in grads if n not in versions]) from e
            raise
        if per_name_err:
            # Per-name refusals from the result manifest (moved mid-
            # reshard, undecodable): surface the PartialPush salvage —
            # and run the stale-advertisement heal for undecodable
            # answers exactly like a per-tensor push would.
            cause = next(iter(per_name_err.values()))
            for err in per_name_err.values():
                self._codec_push_failed(err)
            raise PartialPushError(
                cause, dict(versions),
                [n for n in grads if n not in versions])
        self.pacer.clear()
        return versions

    def close(self) -> None:
        if self._oneside_reader not in (None, False):
            self._oneside_reader.close()
        self._oneside_reader = False
        self.channel.close()
