"""A parameter server whose traffic rides the RPC framework as tensors.

This closes the loop SURVEY.md §2.11/§7 charters: the reference's headline
deployment is parameter-server fan-out over its RDMA transport; here the
served state is jax.Arrays in device memory, and every pull/push crosses
the framework's ``tpu://`` transport as a by-reference TensorArena
attachment (brpc_tpu/runtime/tensor.py):

  PULL:  device param --D2H--> server arena --by-ref--> client maps the
         same pages --jax.device_put--> device replica
  PUSH:  device grad --D2H--> client arena --by-ref--> server applies the
         fused Pallas momentum update ON DEVICE and bumps the version.

Reference mapping: example/parallel_echo_c++ fan-out + rdma payload path
(rdma_endpoint.h:89); the update rule matches ops/fused_update.py so a
local training loop and an RPC-driven one converge identically (asserted
by tests/test_tensor_bridge.py).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import weakref
from typing import Dict, Optional

import jax
import numpy as np

from brpc_tpu.ops.fused_update import fused_momentum_update
from brpc_tpu.runtime import native
from brpc_tpu.runtime.tensor import (PipelineWindow, TensorArena,
                                     TensorChannel, _device_put_from_view,
                                     add_tensor_service)

# App-level error codes, disjoint from trpc/errno.h. The server
# historically answered "no such parameter" with 2007 — which COLLIDES
# with TRPC_ECONNECT, so a fleet client couldn't tell "that shard doesn't
# have it" (don't retry) from "that shard is unreachable" (do retry):
# E_NO_SUCH moves to its own code. E_MOVED's text carries the forwarding
# address as "moved:<host:port>" — the fleet client parses it to re-route
# mid-reshard; E_MIGRATING means installed-but-uncommitted (retry soon).
E_NO_SUCH = 2040
E_MOVED = 2041
E_MIGRATING = 2042
E_EXISTS = 2043  # install over a live (serving) parameter

_MOVED_RE = re.compile(r"moved:(\S+)")


def moved_dest(err: "native.RpcError") -> Optional[str]:
    """The forwarding address an E_MOVED redirect carries, or None."""
    if err.code != E_MOVED:
        return None
    m = _MOVED_RE.search(err.text or "")
    return m.group(1) if m else None


# Process-wide recorders (brpc_tpu/observability): every ParameterServer
# instance feeds the same series, like native per-method stats aggregate.
_metrics_cache = None
_SERVERS: "weakref.WeakSet[ParameterServer]" = weakref.WeakSet()


def _max_version_lag() -> int:
    """Largest (max - min) parameter-version spread across live servers —
    how far the most- and least-updated parameters have drifted apart.
    Reads the lock-free mirror each Push maintains: gauge callbacks run
    at scrape time under the native registry walk, so taking srv._mu here
    would stall every metrics consumer behind an in-flight update."""
    return max((srv._version_spread for srv in list(_SERVERS)), default=0)


def _metrics():
    global _metrics_cache
    if _metrics_cache is None:
        from brpc_tpu.observability import metrics as obs

        _metrics_cache = {
            # HANDLER-BODY time only: Pull's D2H + arena staging happens
            # after the handler returns (add_tensor_service trampoline) —
            # the tensor_handler recorder carries that full server-side
            # cost; the client's tensor_pull carries the end-to-end view.
            "pull": obs.latency("param_server_pull"),
            "push": obs.latency("param_server_push"),
            "push_bytes": obs.counter("param_server_push_bytes"),
            "lag": obs.gauge("param_server_version_lag", _max_version_lag),
        }
    return _metrics_cache


def _per_server_lag_gauge(name: str, srv: "ParameterServer") -> None:
    """Expose this server's version spread as its OWN gauge
    (`param_server_version_lag_<name>`) beside the process-wide max —
    satellite: per-server (and per-shard, via the fleet's shard names)
    version-lag series on /vars, /brpc_metrics and /tensorz. Re-pointable
    (newest server claiming the name wins) and weakly bound, so a test's
    re-created server neither collides nor leaks."""
    from brpc_tpu.observability import metrics as obs

    safe = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    ref = weakref.ref(srv)
    # `safe` is re.sub-sanitized to the exposition charset just above.
    obs.repointable_gauge(
        f"param_server_version_lag_{safe}",  # tpulint: allow(metric-name)
        lambda: getattr(ref(), "_version_spread", 0))


class ParameterServer:
    """Serves named jax.Arrays over RPC; Push applies momentum SGD.

    Shard-aware (brpc_tpu/fleet): Meta carries a schema epoch (bumped when
    the parameter SET changes — Install/Retire — never by plain updates,
    so clients can cache the name->shape/dtype map); Handoff/Install/
    Retire/Commit are the live-resharding handshake a fleet Migrator
    drives. Per-name migration states:

      serving  normal pulls + pushes
      frozen   Handoff exported it: pulls still served (old-owner reads
               until the handoff commits), pushes refused with E_MOVED so
               no update can land that the export missed
      pending  Installed here but not yet committed: pulls served (same
               version the old owner still serves), pushes refused with
               E_MIGRATING until Commit — so a version can never advance
               on the new owner while the old owner still answers reads

    A retired name answers E_MOVED with "moved:<dest>" so clients holding
    a stale shard map re-route without a registry round trip.
    """

    def __init__(self, params: Dict[str, jax.Array], lr: float = 0.01,
                 momentum: float = 0.9, arena: Optional[TensorArena] = None,
                 name: Optional[str] = None):
        # Backend split for the Push hot path. On TPU the update is the
        # fused Pallas kernel over device arrays (device_put = a real H2D
        # DMA). On the CPU backend that same shape is all dispatch
        # overhead: per-push jax dispatch (~0.5ms) dominated the pipelined
        # bench, and device_put ZERO-COPY ALIASES 64B-aligned host buffers
        # — with the update dispatched async, the grad view's arena range
        # could be reused under the pending computation. The CPU path
        # keeps params/momenta as numpy and applies the update
        # synchronously, reading straight from the request view (safe:
        # the read completes before the handler returns and the view
        # releases) — but COPY-ON-WRITE, never in place; see
        # _apply_update for why handed-out arrays must stay immutable.
        self._on_device = jax.default_backend() == "tpu"
        if self._on_device:
            self._params = dict(params)
            self._momenta = {k: jax.numpy.zeros_like(v)
                             for k, v in self._params.items()}
        else:
            self._params = {k: np.array(v) for k, v in params.items()}
            self._momenta = {k: np.zeros_like(v)
                             for k, v in self._params.items()}
        self._version = {k: 0 for k in self._params}
        self._lr = lr
        self._momentum = momentum
        # Per-parameter update locks: pushes to the SAME name must
        # serialize (momentum reads its own previous write), but pushes to
        # different names are independent — and numpy releases the GIL for
        # the 1MB elementwise math, so pipelined pushes of a sharded model
        # really do update in parallel. _mu stays the dict/version lock
        # and is never held while an update lock is taken... the update
        # lock is taken FIRST (fixed order, no cycle).
        self._update_locks = {k: threading.Lock() for k in self._params}
        # Update admission: a pipelined client parks a whole window of
        # pushes on the server at once, and running every update's math
        # concurrently just thrashes the cores the transport needs (the
        # math releases the GIL, so an unbounded pool really does fan
        # out). Cap concurrent update computations near the core count;
        # excess handlers queue on the semaphore (pool pthreads — safe to
        # block) with the wire already overlapped.
        self._update_sem = threading.BoundedSemaphore(
            min(4, max(2, os.cpu_count() or 2)))
        self._mu = threading.Lock()  # handlers run on callback-pool threads
        # Lock-free mirror of max(version)-min(version), updated by Push
        # under _mu, read by the version-lag gauge without it.
        self._version_spread = 0
        # ---- shard-aware state (brpc_tpu/fleet) ----
        # Schema epoch: bumps when the parameter SET changes (Install /
        # Retire), never on plain version bumps — the client Meta cache key.
        self._schema_epoch = 1
        self._state: Dict[str, str] = {}        # absent == "serving"
        self._handoff_dest: Dict[str, str] = {}  # frozen name -> dest addr
        self._moved: Dict[str, str] = {}         # retired name -> dest addr
        self.name = name
        if name is not None:
            _per_server_lag_gauge(name, self)
        _SERVERS.add(self)
        self._m = _metrics()
        self.server = native.Server()
        self.arena = add_tensor_service(self.server, "ParamService",
                                        self._handle, arena)
        self.port: Optional[int] = None

    def start(self, addr: str = "127.0.0.1:0") -> int:
        self.port = self.server.start(addr)
        return self.port

    def stop(self) -> None:
        self.server.stop()

    # ---- handler (runs inside a server fiber) ----
    def _handle(self, method: str, request: bytes, att):
        from brpc_tpu.observability import tracing

        if method == "Meta":
            # Under _mu: Push swaps self._params values and bumps
            # self._version concurrently on other fibers — an unlocked
            # read here can pair a new version with an old shape/dtype
            # (or hit a dict mutated mid-iteration).
            with self._mu:
                meta = {}
                for k, v in self._params.items():
                    entry = {"shape": list(v.shape), "dtype": str(v.dtype),
                             "version": self._version[k]}
                    state = self._state.get(k)
                    if state is not None:  # frozen/pending: the migrator's
                        entry["state"] = state  # repair pass reads this
                    meta[k] = entry
                epoch = self._schema_epoch
            return json.dumps({"epoch": epoch, "params": meta}).encode(), None
        if method == "Epoch":
            # The Meta-cache validator: a tiny small-RPC-fast-path answer
            # (schema epoch only) instead of the full Meta payload.
            with self._mu:
                epoch = self._schema_epoch
            return json.dumps({"epoch": epoch}).encode(), None
        if method == "Handoff":
            return self._handle_handoff(request)
        if method == "Install":
            return self._handle_install(request, att)
        if method == "Retire":
            return self._handle_retire(request)
        if method == "Commit":
            return self._handle_commit(request)
        name = request.decode()
        with self._mu:
            known = name in self._params
            dest = self._moved.get(name)
        if not known:
            if dest is not None:
                raise native.RpcError(E_MOVED,
                                      f"parameter {name} moved:{dest}")
            raise native.RpcError(E_NO_SUCH, f"no such parameter: {name}")
        if method == "Pull":
            t0 = time.monotonic()
            with self._mu:
                if name not in self._params:  # retired under our feet
                    moved = self._moved.get(name)
                    if moved is not None:
                        raise native.RpcError(
                            E_MOVED, f"parameter {name} moved:{moved}")
                    raise native.RpcError(E_NO_SUCH,
                                          f"no such parameter: {name}")
                out = str(self._version[name]).encode(), self._params[name]
            self._m["pull"].record_s(time.monotonic() - t0)
            return out
        if method == "Push":
            if att is None:
                raise native.RpcError(2002, "push without gradient")
            t0 = time.monotonic()
            self._update_sem.acquire()
            try:
                version = self._apply_update(name, att, tracing)
            finally:
                self._update_sem.release()
            self._m["push"].record_s(time.monotonic() - t0)
            self._m["push_bytes"].add(att.nbytes)
            return str(version).encode(), None
        raise native.RpcError(E_NO_SUCH, f"no such method: {method}")

    # ---- live-resharding handshake (driven by brpc_tpu/fleet.Migrator) ----

    def _recompute_spread_locked(self) -> None:
        vs = self._version.values()
        self._version_spread = max(vs) - min(vs) if vs else 0

    def _handle_handoff(self, request: bytes):
        """Freeze `name` for export: pushes refuse with E_MOVED from here
        on (no update can land that the export would miss); pulls keep
        serving the frozen — latest committed — version until Retire.
        Returns {"version"} + the stacked [param, momentum] tensor.
        Idempotent: a migrator retry re-exports the same frozen state."""
        req = json.loads(request.decode())
        name, dest = req["name"], req.get("dest", "")
        with self._mu:
            lock = self._update_locks.get(name)
            if lock is None:
                moved = self._moved.get(name)
                if moved is not None:
                    raise native.RpcError(E_MOVED,
                                          f"parameter {name} moved:{moved}")
                raise native.RpcError(E_NO_SUCH,
                                      f"no such parameter: {name}")
        with lock:  # an in-flight push completes (or sees frozen) first
            with self._mu:
                if name not in self._params:  # retired while we waited
                    moved = self._moved.get(name)
                    raise native.RpcError(
                        E_MOVED, f"parameter {name} retired"
                        + (f"; moved:{moved}" if moved else ""))
                self._state[name] = "frozen"
                if dest:
                    self._handoff_dest[name] = dest
                p = self._params[name]
                m = self._momenta[name]
                version = self._version[name]
        # Updates are functional (p/m replaced, never mutated) and frozen
        # names take no more of them: stacking outside the locks reads
        # stable arrays. One D2H per array on the device path.
        stacked = np.stack([np.asarray(p), np.asarray(m)])
        return json.dumps({"name": name, "version": version}).encode(), stacked

    def _handle_install(self, request: bytes, att):
        """Adopt a handed-off tensor in `pending` state: pulls serve it
        (same version the frozen old owner still answers), pushes refuse
        with E_MIGRATING until Commit — a version can never advance here
        while the old owner still serves reads. Idempotent re-install of a
        pending name is allowed (migrator retry)."""
        req = json.loads(request.decode())
        name = req["name"]
        version = int(req.get("version", 0))
        if att is None:
            raise native.RpcError(1003, "install without tensor payload")
        if att.ndim < 1 or att.shape[0] != 2:
            raise native.RpcError(
                1003, f"install expects stacked [param, momentum], "
                      f"got shape {tuple(att.shape)}")
        # Detach from the sender's arena pages BEFORE the handler returns.
        param = np.array(att[0])
        mom = np.array(att[1])
        if self._on_device:
            param = _device_put_from_view(param, None)
            mom = _device_put_from_view(mom, None)
        with self._mu:
            # Re-install over `pending` (migrator retry) or `frozen` (this
            # shard handed the name off once and a later remap brought it
            # back before the stale copy was retired) is recovery, not a
            # conflict; only a SERVING copy refuses.
            if name in self._params and self._state.get(name) not in (
                    "pending", "frozen"):
                raise native.RpcError(
                    E_EXISTS, f"install over live parameter: {name}")
            self._params[name] = param
            self._momenta[name] = mom
            self._version[name] = version
            self._update_locks.setdefault(name, threading.Lock())
            self._state[name] = "pending"
            self._moved.pop(name, None)  # keys can migrate back later
            self._handoff_dest.pop(name, None)  # any old freeze is void
            self._schema_epoch += 1
            self._recompute_spread_locked()
        return json.dumps({"name": name, "version": version}).encode(), None

    def _handle_retire(self, request: bytes):
        """Drop a handed-off tensor and remember its forwarding address:
        later pulls/pushes answer E_MOVED "moved:<dest>" so stale-mapped
        clients re-route without a registry round trip. Idempotent."""
        req = json.loads(request.decode())
        name, dest = req["name"], req.get("dest", "")
        with self._mu:
            lock = self._update_locks.get(name)
        if lock is not None:
            with lock:
                with self._mu:
                    self._params.pop(name, None)
                    self._momenta.pop(name, None)
                    self._version.pop(name, None)
                    self._update_locks.pop(name, None)
                    self._state.pop(name, None)
                    self._handoff_dest.pop(name, None)
                    if dest:  # an empty dest would forward into "moved:"
                        self._moved[name] = dest  # — unparseable; a plain
                    self._schema_epoch += 1       # drop answers E_NO_SUCH
                    self._recompute_spread_locked()
        else:
            with self._mu:
                if dest and self._moved.get(name) != dest:
                    # Recording a (new) redirect is a schema change too —
                    # without the bump a warm Meta cache on this server
                    # would keep validating against the pre-retire set.
                    self._moved[name] = dest
                    self._schema_epoch += 1
        return json.dumps({"name": name}).encode(), None

    def _handle_commit(self, request: bytes):
        """pending -> serving: the write-side commit point. Ordered by the
        Migrator AFTER the old owner retired, so reads and writes can
        never disagree across the two owners."""
        name = request.decode()
        with self._mu:
            if name not in self._params:
                moved = self._moved.get(name)
                if moved is not None:
                    raise native.RpcError(E_MOVED,
                                          f"parameter {name} moved:{moved}")
                raise native.RpcError(E_NO_SUCH,
                                      f"no such parameter: {name}")
            self._state.pop(name, None)
            # A stale forwarding hint must not outlive the commit: a later
            # dest-less Handoff would re-surface it as a dead redirect.
            self._handoff_dest.pop(name, None)
        return b"ok", None

    def _apply_update(self, name: str, att, tracing) -> int:
        if self._on_device:
            with tracing.stage("device_put"):
                # H2D DMA from the request view, completed (and thus
                # detached from the arena pages) before the handler
                # returns and the view's range can be reused.
                grad = _device_put_from_view(np.ascontiguousarray(att), None)
        with self._mu:
            lock = self._update_locks.get(name)
            if lock is None:  # retired between the known-check and here
                moved = self._moved.get(name)
                raise native.RpcError(
                    E_MOVED, f"parameter {name} retired"
                    + (f"; moved:{moved}" if moved else ""))
        with lock:
            with self._mu:
                if name not in self._params:  # retired while we waited
                    moved = self._moved.get(name)
                    raise native.RpcError(
                        E_MOVED, f"parameter {name} retired"
                        + (f"; moved:{moved}" if moved else ""))
                state = self._state.get(name)
                if state == "frozen":
                    dest = self._handoff_dest.get(name)
                    raise native.RpcError(
                        E_MOVED, f"parameter {name} handed off"
                        + (f"; moved:{dest}" if dest else ""))
                if state == "pending":
                    raise native.RpcError(
                        E_MIGRATING,
                        f"parameter {name} migrating in; retry shortly")
                p = self._params[name]
                m = self._momenta[name]
            with tracing.stage("fused_update"):
                if self._on_device:
                    # Dispatch-only: blocking on device completion here
                    # would serialize every update behind its device
                    # round-trip; JAX's async dispatch already orders
                    # later reads of the new arrays.
                    p2, m2 = fused_momentum_update(
                        p, m, grad.astype(p.dtype),
                        lr=self._lr, beta=self._momentum)
                else:
                    # Copy-on-write numpy momentum step, read straight
                    # from the zero-copy view. NOT in-place: a Pull's
                    # response staging copies the returned array after
                    # the handler drops _mu, so arrays must stay
                    # immutable once handed out (same discipline as the
                    # jax path's functional update).
                    g = att.astype(p.dtype, copy=False)
                    m2 = self._momentum * m + g
                    p2 = p - self._lr * m2
            with self._mu:
                self._params[name] = p2
                self._momenta[name] = m2
                self._version[name] += 1
                version = self._version[name]
                self._recompute_spread_locked()
        return version


class ParameterClient:
    """Pulls params into device arrays / pushes device grads, all over the
    framework (one TensorChannel per client)."""

    def __init__(self, addr: str, arena: Optional[TensorArena] = None):
        self.addr = addr
        self.channel = TensorChannel(addr, arena)
        # Meta cache keyed by the server's schema epoch: the epoch bumps
        # only when the parameter SET changes (Install/Retire), so the
        # name -> shape/dtype map stays valid across ordinary pushes.
        # Cached VERSIONS are stale by design — versions ride each pull.
        self._meta_epoch: Optional[int] = None
        self._meta_cache: Optional[dict] = None

    def meta(self) -> dict:
        payload, _ = self.channel.call("ParamService/Meta")
        doc = json.loads(payload.decode())
        self._meta_epoch = doc["epoch"]
        self._meta_cache = doc["params"]
        return doc["params"]

    def epoch(self) -> int:
        """The server's schema epoch (a tiny small-RPC-fast-path call)."""
        payload, _ = self.channel.call("ParamService/Epoch")
        return json.loads(payload.decode())["epoch"]

    def cached_meta(self) -> dict:
        """The Meta map through the epoch-validated cache: one Epoch
        round trip (bytes, not the whole schema) when warm; a full Meta
        fetch only on the first call or an epoch mismatch."""
        if self._meta_cache is not None and self.epoch() == self._meta_epoch:
            return self._meta_cache
        return self.meta()

    def pull(self, name: str, device=None):
        """-> (version, jax.Array) — H2D straight from the shared pages."""
        rest, arr = self.channel.pull_device("ParamService/Pull",
                                             request=name.encode(),
                                             device=device)
        return int(rest.decode()), arr

    def push_grad(self, name: str, grad) -> int:
        """Send a device gradient; returns the server's new version."""
        payload = self.channel.push_device("ParamService/Push", grad,
                                           request=name.encode())
        return int(payload.decode())

    # ---- live-resharding handshake (used by brpc_tpu/fleet.Migrator) ----

    def handoff(self, name: str, dest: str = ""):
        """Freeze + export `name` -> (version, stacked [param, momentum]
        host array). The server refuses pushes to it from now on."""
        req = json.dumps({"name": name, "dest": dest}).encode()
        payload, stacked = self.channel.call("ParamService/Handoff",
                                             request=req)
        return json.loads(payload.decode())["version"], stacked

    def install(self, name: str, stacked, version: int,
                commit: bool = False) -> None:
        """Adopt a stacked [param, momentum] tensor at `version` in
        pending state; `commit=True` also flips it serving (reseed path)."""
        req = json.dumps({"name": name, "version": int(version)}).encode()
        self.channel.call("ParamService/Install", array=stacked, request=req)
        if commit:
            self.commit(name)

    def retire(self, name: str, dest: str = "") -> None:
        req = json.dumps({"name": name, "dest": dest}).encode()
        self.channel.call("ParamService/Retire", request=req)

    def commit(self, name: str) -> None:
        self.channel.call("ParamService/Commit", request=name.encode())

    # ---- pipelined multi-tensor hot path (PipelineWindow) ----
    # The serial pull/push above pay one full round-trip per tensor: a
    # model with N parameter tensors pays N x the ~260us 1MB latency
    # floor (PERF.md round 3) although the transport sustains ~3x the
    # single-stream throughput at conc=8 (BENCH r05). These keep a
    # bounded window of RPCs in flight instead, so N tensors cost ~1
    # round-trip plus N wire times.

    def pull_all(self, names=None, device=None, window: int = 4
                 ) -> Dict[str, tuple]:
        """Pull many parameters through one bounded pipeline window.

        -> ``{name: (version, jax.Array)}``. Every tensor is
        ``jax.device_put`` STRAIGHT from its zero-copy response view (the
        peer's arena pages) — no intermediate host copy — overlapped with
        the wire transfer of the next tensor. ``names=None`` pulls every
        parameter the server's Meta lists.
        """
        from brpc_tpu.runtime.tensor import _metrics, consume_pull_reply

        if names is None:
            names = sorted(self.cached_meta())
        m = _metrics()
        out: Dict[str, tuple] = {}

        def on_reply(name, payload, view):
            rest, dev, nbytes = consume_pull_reply(payload, view, device)
            m["pull_bytes"].add(nbytes)
            out[name] = (int(rest.decode()), dev)

        with PipelineWindow(self.channel, window, on_reply=on_reply) as win:
            for name in names:
                win.submit("ParamService/Pull", request=name.encode(),
                           tag=name)
        return out

    def push_all(self, grads: Dict[str, object], window: int = 4
                 ) -> Dict[str, int]:
        """Push many gradients through one bounded pipeline window.

        -> ``{name: new_version}``. Staging (D2H + arena memcpy) of
        gradient k+1 overlaps the wire transfer of gradient k; the client
        arena never holds more than ``window`` staged gradients.
        """
        from brpc_tpu.runtime.tensor import _metrics
        m = _metrics()
        versions: Dict[str, int] = {}

        def on_reply(name, payload, view):
            view.release()  # push responses carry no tensor
            versions[name] = int(payload.decode())

        with PipelineWindow(self.channel, window, on_reply=on_reply) as win:
            for name, grad in grads.items():
                win.submit("ParamService/Push", array=grad,
                           request=name.encode(), tag=name)
                m["push_bytes"].add(int(getattr(grad, "nbytes", 0)))
        return versions

    def close(self) -> None:
        self.channel.close()
