"""A parameter server whose traffic rides the RPC framework as tensors.

This closes the loop SURVEY.md §2.11/§7 charters: the reference's headline
deployment is parameter-server fan-out over its RDMA transport; here the
served state is jax.Arrays in device memory, and every pull/push crosses
the framework's ``tpu://`` transport as a by-reference TensorArena
attachment (brpc_tpu/runtime/tensor.py):

  PULL:  device param --D2H--> server arena --by-ref--> client maps the
         same pages --jax.device_put--> device replica
  PUSH:  device grad --D2H--> client arena --by-ref--> server applies the
         fused Pallas momentum update ON DEVICE and bumps the version.

Reference mapping: example/parallel_echo_c++ fan-out + rdma payload path
(rdma_endpoint.h:89); the update rule matches ops/fused_update.py so a
local training loop and an RPC-driven one converge identically (asserted
by tests/test_tensor_bridge.py).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Optional

import jax
import numpy as np

from brpc_tpu.ops.fused_update import fused_momentum_update
from brpc_tpu.runtime import native
from brpc_tpu.runtime.tensor import (TensorArena, TensorChannel,
                                     add_tensor_service)


class ParameterServer:
    """Serves named jax.Arrays over RPC; Push applies momentum SGD."""

    def __init__(self, params: Dict[str, jax.Array], lr: float = 0.01,
                 momentum: float = 0.9, arena: Optional[TensorArena] = None):
        self._params = dict(params)
        self._momenta = {k: jax.numpy.zeros_like(v)
                         for k, v in self._params.items()}
        self._version = {k: 0 for k in self._params}
        self._lr = lr
        self._mu = threading.Lock()  # handlers run on fiber workers
        self.server = native.Server()
        self.arena = add_tensor_service(self.server, "ParamService",
                                        self._handle, arena)
        self.port: Optional[int] = None

    def start(self, addr: str = "127.0.0.1:0") -> int:
        self.port = self.server.start(addr)
        return self.port

    def stop(self) -> None:
        self.server.stop()

    # ---- handler (runs inside a server fiber) ----
    def _handle(self, method: str, request: bytes, att):
        if method == "Meta":
            meta = {k: {"shape": list(v.shape), "dtype": str(v.dtype),
                        "version": self._version[k]}
                    for k, v in self._params.items()}
            return json.dumps(meta).encode(), None
        name = request.decode()
        if name not in self._params:
            raise native.RpcError(2007, f"no such parameter: {name}")
        if method == "Pull":
            with self._mu:
                return str(self._version[name]).encode(), self._params[name]
        if method == "Push":
            if att is None:
                raise native.RpcError(2002, "push without gradient")
            grad = jax.device_put(np.ascontiguousarray(att))
            with self._mu:
                p, m = fused_momentum_update(
                    self._params[name], self._momenta[name],
                    grad.astype(self._params[name].dtype),
                    lr=self._lr)
                self._params[name] = p
                self._momenta[name] = m
                self._version[name] += 1
                return str(self._version[name]).encode(), None
        raise native.RpcError(2007, f"no such method: {method}")


class ParameterClient:
    """Pulls params into device arrays / pushes device grads, all over the
    framework (one TensorChannel per client)."""

    def __init__(self, addr: str, arena: Optional[TensorArena] = None):
        self.channel = TensorChannel(addr, arena)

    def meta(self) -> dict:
        payload, _ = self.channel.call("ParamService/Meta")
        return json.loads(payload.decode())

    def pull(self, name: str, device=None):
        """-> (version, jax.Array) — H2D straight from the shared pages."""
        rest, arr = self.channel.pull_device("ParamService/Pull",
                                             request=name.encode(),
                                             device=device)
        return int(rest.decode()), arr

    def push_grad(self, name: str, grad) -> int:
        """Send a device gradient; returns the server's new version."""
        payload = self.channel.push_device("ParamService/Push", grad,
                                           request=name.encode())
        return int(payload.decode())

    def close(self) -> None:
        self.channel.close()
