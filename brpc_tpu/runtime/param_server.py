"""A parameter server whose traffic rides the RPC framework as tensors.

This closes the loop SURVEY.md §2.11/§7 charters: the reference's headline
deployment is parameter-server fan-out over its RDMA transport; here the
served state is jax.Arrays in device memory, and every pull/push crosses
the framework's ``tpu://`` transport as a by-reference TensorArena
attachment (brpc_tpu/runtime/tensor.py):

  PULL:  device param --D2H--> server arena --by-ref--> client maps the
         same pages --jax.device_put--> device replica
  PUSH:  device grad --D2H--> client arena --by-ref--> server applies the
         fused Pallas momentum update ON DEVICE and bumps the version.

Reference mapping: example/parallel_echo_c++ fan-out + rdma payload path
(rdma_endpoint.h:89); the update rule matches ops/fused_update.py so a
local training loop and an RPC-driven one converge identically (asserted
by tests/test_tensor_bridge.py).
"""

from __future__ import annotations

import json
import threading
import time
import weakref
from typing import Dict, Optional

import jax
import numpy as np

from brpc_tpu.ops.fused_update import fused_momentum_update
from brpc_tpu.runtime import native
from brpc_tpu.runtime.tensor import (TensorArena, TensorChannel,
                                     add_tensor_service)

# Process-wide recorders (brpc_tpu/observability): every ParameterServer
# instance feeds the same series, like native per-method stats aggregate.
_metrics_cache = None
_SERVERS: "weakref.WeakSet[ParameterServer]" = weakref.WeakSet()


def _max_version_lag() -> int:
    """Largest (max - min) parameter-version spread across live servers —
    how far the most- and least-updated parameters have drifted apart.
    Reads the lock-free mirror each Push maintains: gauge callbacks run
    at scrape time under the native registry walk, so taking srv._mu here
    would stall every metrics consumer behind an in-flight update."""
    return max((srv._version_spread for srv in list(_SERVERS)), default=0)


def _metrics():
    global _metrics_cache
    if _metrics_cache is None:
        from brpc_tpu.observability import metrics as obs

        _metrics_cache = {
            # HANDLER-BODY time only: Pull's D2H + arena staging happens
            # after the handler returns (add_tensor_service trampoline) —
            # the tensor_handler recorder carries that full server-side
            # cost; the client's tensor_pull carries the end-to-end view.
            "pull": obs.latency("param_server_pull"),
            "push": obs.latency("param_server_push"),
            "push_bytes": obs.counter("param_server_push_bytes"),
            "lag": obs.gauge("param_server_version_lag", _max_version_lag),
        }
    return _metrics_cache


class ParameterServer:
    """Serves named jax.Arrays over RPC; Push applies momentum SGD."""

    def __init__(self, params: Dict[str, jax.Array], lr: float = 0.01,
                 momentum: float = 0.9, arena: Optional[TensorArena] = None):
        self._params = dict(params)
        self._momenta = {k: jax.numpy.zeros_like(v)
                         for k, v in self._params.items()}
        self._version = {k: 0 for k in self._params}
        self._lr = lr
        self._mu = threading.Lock()  # handlers run on callback-pool threads
        # Lock-free mirror of max(version)-min(version), updated by Push
        # under _mu, read by the version-lag gauge without it.
        self._version_spread = 0
        _SERVERS.add(self)
        self._m = _metrics()
        self.server = native.Server()
        self.arena = add_tensor_service(self.server, "ParamService",
                                        self._handle, arena)
        self.port: Optional[int] = None

    def start(self, addr: str = "127.0.0.1:0") -> int:
        self.port = self.server.start(addr)
        return self.port

    def stop(self) -> None:
        self.server.stop()

    # ---- handler (runs inside a server fiber) ----
    def _handle(self, method: str, request: bytes, att):
        from brpc_tpu.observability import tracing

        if method == "Meta":
            # Under _mu: Push swaps self._params values and bumps
            # self._version concurrently on other fibers — an unlocked
            # read here can pair a new version with an old shape/dtype
            # (or hit a dict mutated mid-iteration).
            with self._mu:
                meta = {k: {"shape": list(v.shape), "dtype": str(v.dtype),
                            "version": self._version[k]}
                        for k, v in self._params.items()}
            return json.dumps(meta).encode(), None
        name = request.decode()
        with self._mu:
            known = name in self._params
        if not known:
            raise native.RpcError(2007, f"no such parameter: {name}")
        if method == "Pull":
            t0 = time.monotonic()
            with self._mu:
                out = str(self._version[name]).encode(), self._params[name]
            self._m["pull"].record_s(time.monotonic() - t0)
            return out
        if method == "Push":
            if att is None:
                raise native.RpcError(2002, "push without gradient")
            t0 = time.monotonic()
            with tracing.stage("device_put"):
                grad = jax.device_put(np.ascontiguousarray(att))
            with self._mu:
                # Dispatch-only timing: blocking on device completion here
                # would serialize Pull/Meta (and the version-lag gauge)
                # behind every update's device round-trip; JAX's async
                # dispatch already orders later reads of the new arrays.
                with tracing.stage("fused_update"):
                    p, m = fused_momentum_update(
                        self._params[name], self._momenta[name],
                        grad.astype(self._params[name].dtype),
                        lr=self._lr)
                self._params[name] = p
                self._momenta[name] = m
                self._version[name] += 1
                version = self._version[name]
                vs = self._version.values()
                self._version_spread = max(vs) - min(vs)
            self._m["push"].record_s(time.monotonic() - t0)
            self._m["push_bytes"].add(att.nbytes)
            return str(version).encode(), None
        raise native.RpcError(2007, f"no such method: {method}")


class ParameterClient:
    """Pulls params into device arrays / pushes device grads, all over the
    framework (one TensorChannel per client)."""

    def __init__(self, addr: str, arena: Optional[TensorArena] = None):
        self.channel = TensorChannel(addr, arena)

    def meta(self) -> dict:
        payload, _ = self.channel.call("ParamService/Meta")
        return json.loads(payload.decode())

    def pull(self, name: str, device=None):
        """-> (version, jax.Array) — H2D straight from the shared pages."""
        rest, arr = self.channel.pull_device("ParamService/Pull",
                                             request=name.encode(),
                                             device=device)
        return int(rest.decode()), arr

    def push_grad(self, name: str, grad) -> int:
        """Send a device gradient; returns the server's new version."""
        payload = self.channel.push_device("ParamService/Push", grad,
                                           request=name.encode())
        return int(payload.decode())

    def close(self) -> None:
        self.channel.close()
