"""A parameter server whose traffic rides the RPC framework as tensors.

This closes the loop SURVEY.md §2.11/§7 charters: the reference's headline
deployment is parameter-server fan-out over its RDMA transport; here the
served state is jax.Arrays in device memory, and every pull/push crosses
the framework's ``tpu://`` transport as a by-reference TensorArena
attachment (brpc_tpu/runtime/tensor.py):

  PULL:  device param --D2H--> server arena --by-ref--> client maps the
         same pages --jax.device_put--> device replica
  PUSH:  device grad --D2H--> client arena --by-ref--> server applies the
         fused Pallas momentum update ON DEVICE and bumps the version.

Reference mapping: example/parallel_echo_c++ fan-out + rdma payload path
(rdma_endpoint.h:89); the update rule matches ops/fused_update.py so a
local training loop and an RPC-driven one converge identically (asserted
by tests/test_tensor_bridge.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Dict, Optional

import jax
import numpy as np

from brpc_tpu.ops.fused_update import fused_momentum_update
from brpc_tpu.runtime import native
from brpc_tpu.runtime.tensor import (PipelineWindow, TensorArena,
                                     TensorChannel, _device_put_from_view,
                                     add_tensor_service)

# Process-wide recorders (brpc_tpu/observability): every ParameterServer
# instance feeds the same series, like native per-method stats aggregate.
_metrics_cache = None
_SERVERS: "weakref.WeakSet[ParameterServer]" = weakref.WeakSet()


def _max_version_lag() -> int:
    """Largest (max - min) parameter-version spread across live servers —
    how far the most- and least-updated parameters have drifted apart.
    Reads the lock-free mirror each Push maintains: gauge callbacks run
    at scrape time under the native registry walk, so taking srv._mu here
    would stall every metrics consumer behind an in-flight update."""
    return max((srv._version_spread for srv in list(_SERVERS)), default=0)


def _metrics():
    global _metrics_cache
    if _metrics_cache is None:
        from brpc_tpu.observability import metrics as obs

        _metrics_cache = {
            # HANDLER-BODY time only: Pull's D2H + arena staging happens
            # after the handler returns (add_tensor_service trampoline) —
            # the tensor_handler recorder carries that full server-side
            # cost; the client's tensor_pull carries the end-to-end view.
            "pull": obs.latency("param_server_pull"),
            "push": obs.latency("param_server_push"),
            "push_bytes": obs.counter("param_server_push_bytes"),
            "lag": obs.gauge("param_server_version_lag", _max_version_lag),
        }
    return _metrics_cache


class ParameterServer:
    """Serves named jax.Arrays over RPC; Push applies momentum SGD."""

    def __init__(self, params: Dict[str, jax.Array], lr: float = 0.01,
                 momentum: float = 0.9, arena: Optional[TensorArena] = None):
        # Backend split for the Push hot path. On TPU the update is the
        # fused Pallas kernel over device arrays (device_put = a real H2D
        # DMA). On the CPU backend that same shape is all dispatch
        # overhead: per-push jax dispatch (~0.5ms) dominated the pipelined
        # bench, and device_put ZERO-COPY ALIASES 64B-aligned host buffers
        # — with the update dispatched async, the grad view's arena range
        # could be reused under the pending computation. The CPU path
        # keeps params/momenta as numpy and applies the update
        # synchronously, reading straight from the request view (safe:
        # the read completes before the handler returns and the view
        # releases) — but COPY-ON-WRITE, never in place; see
        # _apply_update for why handed-out arrays must stay immutable.
        self._on_device = jax.default_backend() == "tpu"
        if self._on_device:
            self._params = dict(params)
            self._momenta = {k: jax.numpy.zeros_like(v)
                             for k, v in self._params.items()}
        else:
            self._params = {k: np.array(v) for k, v in params.items()}
            self._momenta = {k: np.zeros_like(v)
                             for k, v in self._params.items()}
        self._version = {k: 0 for k in self._params}
        self._lr = lr
        self._momentum = momentum
        # Per-parameter update locks: pushes to the SAME name must
        # serialize (momentum reads its own previous write), but pushes to
        # different names are independent — and numpy releases the GIL for
        # the 1MB elementwise math, so pipelined pushes of a sharded model
        # really do update in parallel. _mu stays the dict/version lock
        # and is never held while an update lock is taken... the update
        # lock is taken FIRST (fixed order, no cycle).
        self._update_locks = {k: threading.Lock() for k in self._params}
        # Update admission: a pipelined client parks a whole window of
        # pushes on the server at once, and running every update's math
        # concurrently just thrashes the cores the transport needs (the
        # math releases the GIL, so an unbounded pool really does fan
        # out). Cap concurrent update computations near the core count;
        # excess handlers queue on the semaphore (pool pthreads — safe to
        # block) with the wire already overlapped.
        self._update_sem = threading.BoundedSemaphore(
            min(4, max(2, os.cpu_count() or 2)))
        self._mu = threading.Lock()  # handlers run on callback-pool threads
        # Lock-free mirror of max(version)-min(version), updated by Push
        # under _mu, read by the version-lag gauge without it.
        self._version_spread = 0
        _SERVERS.add(self)
        self._m = _metrics()
        self.server = native.Server()
        self.arena = add_tensor_service(self.server, "ParamService",
                                        self._handle, arena)
        self.port: Optional[int] = None

    def start(self, addr: str = "127.0.0.1:0") -> int:
        self.port = self.server.start(addr)
        return self.port

    def stop(self) -> None:
        self.server.stop()

    # ---- handler (runs inside a server fiber) ----
    def _handle(self, method: str, request: bytes, att):
        from brpc_tpu.observability import tracing

        if method == "Meta":
            # Under _mu: Push swaps self._params values and bumps
            # self._version concurrently on other fibers — an unlocked
            # read here can pair a new version with an old shape/dtype
            # (or hit a dict mutated mid-iteration).
            with self._mu:
                meta = {k: {"shape": list(v.shape), "dtype": str(v.dtype),
                            "version": self._version[k]}
                        for k, v in self._params.items()}
            return json.dumps(meta).encode(), None
        name = request.decode()
        with self._mu:
            known = name in self._params
        if not known:
            raise native.RpcError(2007, f"no such parameter: {name}")
        if method == "Pull":
            t0 = time.monotonic()
            with self._mu:
                out = str(self._version[name]).encode(), self._params[name]
            self._m["pull"].record_s(time.monotonic() - t0)
            return out
        if method == "Push":
            if att is None:
                raise native.RpcError(2002, "push without gradient")
            t0 = time.monotonic()
            self._update_sem.acquire()
            try:
                version = self._apply_update(name, att, tracing)
            finally:
                self._update_sem.release()
            self._m["push"].record_s(time.monotonic() - t0)
            self._m["push_bytes"].add(att.nbytes)
            return str(version).encode(), None
        raise native.RpcError(2007, f"no such method: {method}")

    def _apply_update(self, name: str, att, tracing) -> int:
        if self._on_device:
            with tracing.stage("device_put"):
                # H2D DMA from the request view, completed (and thus
                # detached from the arena pages) before the handler
                # returns and the view's range can be reused.
                grad = _device_put_from_view(np.ascontiguousarray(att), None)
        with self._update_locks[name]:
            with self._mu:
                p = self._params[name]
                m = self._momenta[name]
            with tracing.stage("fused_update"):
                if self._on_device:
                    # Dispatch-only: blocking on device completion here
                    # would serialize every update behind its device
                    # round-trip; JAX's async dispatch already orders
                    # later reads of the new arrays.
                    p2, m2 = fused_momentum_update(
                        p, m, grad.astype(p.dtype),
                        lr=self._lr, beta=self._momentum)
                else:
                    # Copy-on-write numpy momentum step, read straight
                    # from the zero-copy view. NOT in-place: a Pull's
                    # response staging copies the returned array after
                    # the handler drops _mu, so arrays must stay
                    # immutable once handed out (same discipline as the
                    # jax path's functional update).
                    g = att.astype(p.dtype, copy=False)
                    m2 = self._momentum * m + g
                    p2 = p - self._lr * m2
            with self._mu:
                self._params[name] = p2
                self._momenta[name] = m2
                self._version[name] += 1
                version = self._version[name]
                vs = self._version.values()
                self._version_spread = max(vs) - min(vs)
        return version


class ParameterClient:
    """Pulls params into device arrays / pushes device grads, all over the
    framework (one TensorChannel per client)."""

    def __init__(self, addr: str, arena: Optional[TensorArena] = None):
        self.channel = TensorChannel(addr, arena)

    def meta(self) -> dict:
        payload, _ = self.channel.call("ParamService/Meta")
        return json.loads(payload.decode())

    def pull(self, name: str, device=None):
        """-> (version, jax.Array) — H2D straight from the shared pages."""
        rest, arr = self.channel.pull_device("ParamService/Pull",
                                             request=name.encode(),
                                             device=device)
        return int(rest.decode()), arr

    def push_grad(self, name: str, grad) -> int:
        """Send a device gradient; returns the server's new version."""
        payload = self.channel.push_device("ParamService/Push", grad,
                                           request=name.encode())
        return int(payload.decode())

    # ---- pipelined multi-tensor hot path (PipelineWindow) ----
    # The serial pull/push above pay one full round-trip per tensor: a
    # model with N parameter tensors pays N x the ~260us 1MB latency
    # floor (PERF.md round 3) although the transport sustains ~3x the
    # single-stream throughput at conc=8 (BENCH r05). These keep a
    # bounded window of RPCs in flight instead, so N tensors cost ~1
    # round-trip plus N wire times.

    def pull_all(self, names=None, device=None, window: int = 4
                 ) -> Dict[str, tuple]:
        """Pull many parameters through one bounded pipeline window.

        -> ``{name: (version, jax.Array)}``. Every tensor is
        ``jax.device_put`` STRAIGHT from its zero-copy response view (the
        peer's arena pages) — no intermediate host copy — overlapped with
        the wire transfer of the next tensor. ``names=None`` pulls every
        parameter the server's Meta lists.
        """
        from brpc_tpu.runtime.tensor import _metrics, consume_pull_reply

        if names is None:
            names = sorted(self.meta())
        m = _metrics()
        out: Dict[str, tuple] = {}

        def on_reply(name, payload, view):
            rest, dev, nbytes = consume_pull_reply(payload, view, device)
            m["pull_bytes"].add(nbytes)
            out[name] = (int(rest.decode()), dev)

        with PipelineWindow(self.channel, window, on_reply=on_reply) as win:
            for name in names:
                win.submit("ParamService/Pull", request=name.encode(),
                           tag=name)
        return out

    def push_all(self, grads: Dict[str, object], window: int = 4
                 ) -> Dict[str, int]:
        """Push many gradients through one bounded pipeline window.

        -> ``{name: new_version}``. Staging (D2H + arena memcpy) of
        gradient k+1 overlaps the wire transfer of gradient k; the client
        arena never holds more than ``window`` staged gradients.
        """
        from brpc_tpu.runtime.tensor import _metrics
        m = _metrics()
        versions: Dict[str, int] = {}

        def on_reply(name, payload, view):
            view.release()  # push responses carry no tensor
            versions[name] = int(payload.decode())

        with PipelineWindow(self.channel, window, on_reply=on_reply) as win:
            for name, grad in grads.items():
                win.submit("ParamService/Push", array=grad,
                           request=name.encode(), tag=name)
                m["push_bytes"].add(int(getattr(grad, "nbytes", 0)))
        return versions

    def close(self) -> None:
        self.channel.close()
