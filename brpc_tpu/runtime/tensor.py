"""Tensor-on-the-wire: jax.Array payloads riding the RPC framework.

This is the Python face of the native TensorArena bridge
(native/ttpu/tensor_arena.h — the tpu-native analog of the reference's
RDMA memory registration, rdma_helper.h:48): a shm-backed arena both ends
of a ``tpu://`` connection map. The flow per tensor:

  device array --(one D2H DMA)--> arena pages --(by-reference doorbell)-->
  receiver reads the SAME physical pages in place --(jax.device_put)-->
  device array on the other side.

No host-side copies happen between the arena and the receiving handler:
the IOBuf blocks on both sides point into the shared mapping (pointer
identity is asserted by native/test/test_tensor_arena.cpp). The staging
copy INTO the arena is the registered-memory discipline the reference's
RDMA path uses too (app data lands in registered blocks before the NIC
sees it); on a real pod the arena plays the pinned-host staging buffer
role that libtpu DMAs from.

Typed tensors ride as: request/response payload = a tiny metadata header
(dtype/shape, msgpack-free manual encoding), attachment = the raw bytes in
the arena.
"""

from __future__ import annotations

import ctypes
import json
import struct
import threading
import time
from collections import deque
from typing import Callable, Optional, Tuple

import numpy as np

from brpc_tpu.runtime import native
from brpc_tpu.runtime.native import RpcError, fill_err_text, lib

# App-level error code (param_server.py holds the rest of the 2040+ range:
# E_NO_SUCH..E_EXISTS at 2040-2043): a typed tensor send whose decoded
# meta header cannot be applied to the payload (truncated/corrupt
# quantized bytes, a codec this build can't parse). Deliberately NOT
# 2004/TRPC_EINTERNAL — the client-side codec self-heal keys on this
# code, and app codes must never collide with transport codes.
E_UNDECODABLE = 2044


def _bind_tensor_api(L: ctypes.CDLL) -> ctypes.CDLL:
    if getattr(L, "_tensor_api_bound", False):
        return L
    L.tbrpc_arena_create.restype = ctypes.c_void_p
    L.tbrpc_arena_create.argtypes = [ctypes.c_size_t]
    L.tbrpc_arena_destroy.argtypes = [ctypes.c_void_p]
    L.tbrpc_arena_base.restype = ctypes.c_void_p
    L.tbrpc_arena_base.argtypes = [ctypes.c_void_p]
    L.tbrpc_arena_alloc.restype = ctypes.c_int64
    L.tbrpc_arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    L.tbrpc_arena_free.restype = ctypes.c_int
    L.tbrpc_arena_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    L.tbrpc_arena_busy_bytes.restype = ctypes.c_int64
    L.tbrpc_arena_busy_bytes.argtypes = [ctypes.c_void_p]
    L.tbrpc_arenas_busy_bytes.restype = ctypes.c_int64
    L.tbrpc_arenas_busy_bytes.argtypes = []
    L.tbrpc_arenas_total_bytes.restype = ctypes.c_int64
    L.tbrpc_arenas_total_bytes.argtypes = []
    L.tbrpc_var_arena_gauges_create.argtypes = []
    L.tbrpc_arena_wait_reusable.restype = ctypes.c_int
    L.tbrpc_arena_wait_reusable.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64]
    L.tbrpc_call_tensor.restype = ctypes.c_int
    L.tbrpc_call_tensor.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_int),
        ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_view_free.argtypes = [ctypes.c_void_p]
    L.tbrpc_server_add_tensor_service.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, _TENSOR_CB, ctypes.c_void_p]
    # ---- async tensor RPC (futures over the native async CallMethod) ----
    L.tbrpc_call_tensor_async.restype = ctypes.c_void_p
    L.tbrpc_call_tensor_async.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_size_t,
        _TENSOR_DONE_CB, ctypes.c_void_p]
    _future_outs = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_int),
        ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_future_wait.restype = ctypes.c_int
    L.tbrpc_future_wait.argtypes = [ctypes.c_void_p] + _future_outs
    L.tbrpc_future_timed_wait.restype = ctypes.c_int
    L.tbrpc_future_timed_wait.argtypes = [
        ctypes.c_void_p, ctypes.c_int64] + _future_outs
    L.tbrpc_future_cancel.restype = ctypes.c_int
    L.tbrpc_future_cancel.argtypes = [ctypes.c_void_p]
    L.tbrpc_future_destroy.argtypes = [ctypes.c_void_p]
    L.tbrpc_async_inflight.restype = ctypes.c_int64
    L.tbrpc_async_inflight.argtypes = []
    # ---- one-sided tensor reads (published arena windows) ----
    L.tbrpc_oneside_window_create.restype = ctypes.c_void_p
    L.tbrpc_oneside_window_create.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
    L.tbrpc_oneside_window_destroy.argtypes = [ctypes.c_void_p]
    L.tbrpc_oneside_publish.restype = ctypes.c_int
    L.tbrpc_oneside_publish.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_uint64, ctypes.c_int]
    L.tbrpc_oneside_begin_rewrite.argtypes = [ctypes.c_void_p,
                                              ctypes.c_char_p]
    L.tbrpc_oneside_unpublish.restype = ctypes.c_int
    L.tbrpc_oneside_unpublish.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    L.tbrpc_oneside_window_describe.restype = ctypes.c_int64
    L.tbrpc_oneside_window_describe.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_oneside_map.restype = ctypes.c_void_p
    L.tbrpc_oneside_map.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64]
    L.tbrpc_oneside_read.restype = ctypes.c_int
    L.tbrpc_oneside_read.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
    L.tbrpc_oneside_stat.restype = ctypes.c_int
    L.tbrpc_oneside_stat.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64)]
    L.tbrpc_oneside_read_into.restype = ctypes.c_int
    L.tbrpc_oneside_read_into.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
    L.tbrpc_oneside_unmap.restype = ctypes.c_int
    L.tbrpc_oneside_unmap.argtypes = [ctypes.c_void_p]
    L.tbrpc_oneside_stats_json.restype = ctypes.c_int64
    L.tbrpc_oneside_stats_json.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    L._tensor_api_bound = True
    return L


# Completion notification for tbrpc_call_tensor_async: fired on a
# callback-pool pthread BEFORE the future becomes waitable, with the same
# values a wait would return — ownership stays with the future (the
# callback must not free anything).
_TENSOR_DONE_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_void_p,                    # ctx
    ctypes.c_int,                       # status (0 = ok)
    ctypes.c_void_p, ctypes.c_size_t,   # resp
    ctypes.c_void_p,                    # view handle
    ctypes.c_void_p, ctypes.c_size_t,   # ratt ptr/len
    ctypes.c_int,                       # ratt_copied
    ctypes.c_char_p,                    # err_text
)

_TENSOR_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_void_p,                    # ctx
    ctypes.c_char_p,                    # method
    ctypes.c_void_p, ctypes.c_size_t,   # req
    ctypes.c_void_p, ctypes.c_size_t,   # attachment, IN PLACE
    ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),  # resp
    ctypes.POINTER(ctypes.c_void_p),    # resp_arena
    ctypes.POINTER(ctypes.c_uint64),    # resp_att_off
    ctypes.POINTER(ctypes.c_size_t),    # resp_att_len
    ctypes.POINTER(ctypes.c_int),       # resp_att_autofree
    ctypes.POINTER(ctypes.c_int),       # error_code
    ctypes.c_void_p, ctypes.c_size_t,   # err_text buffer (C-owned)
)


# ---- data-plane metrics (brpc_tpu/observability) ----
# Created lazily on first use: importing this module must not load the
# native library. One process-wide set — every channel/arena feeds the
# same recorders, mirroring how the native side aggregates per-method.

_metrics_cache = None


def _metrics():
    global _metrics_cache
    if _metrics_cache is None:
        from brpc_tpu.observability import metrics as obs

        L = _bind_tensor_api(lib())
        # Arena occupancy gauges (tensor_arena_busy_bytes/_total_bytes)
        # are NATIVE PassiveStatus vars over every live arena — created
        # through the capi but evaluated entirely in C++, so scrapes pay
        # no callback-pool hop or GIL, and a closing arena can't race the
        # walk. They ride /vars + /brpc_metrics + /tensorz like the rest.
        L.tbrpc_var_arena_gauges_create()
        _metrics_cache = {
            "pull": obs.latency("tensor_pull"),
            "push": obs.latency("tensor_push"),
            "pull_bytes": obs.counter("tensor_pull_bytes"),
            "push_bytes": obs.counter("tensor_push_bytes"),
            "wait_stalls": obs.counter("tensor_arena_wait_stalls"),
            # Server-side complement of the client recorders: the FULL
            # per-request cost of a Python tensor service — handler body
            # PLUS response staging into the arena (which happens after
            # the handler returns, so per-service recorders can't see it).
            "serve": obs.latency("tensor_handler"),
            # One-sided pull routing: hits read the peer's published
            # window directly (no RPC); fallbacks took the two-sided
            # path (off-host, unmapped, unpublished name, torn budget).
            # The native side keeps its own oneside_* adders; these two
            # count the CLIENT-side routing decision.
            "oneside_hits": obs.counter("oneside_pull_hits"),
            "oneside_fallbacks": obs.counter("oneside_pull_fallbacks"),
        }
    return _metrics_cache


def _stage(name):
    from brpc_tpu.observability import tracing

    return tracing.stage(name)


# ---- pipeline in-flight gauge ----
# One process-wide gauge over every live PipelineWindow (tbvar names are a
# process-wide namespace — per-window registrations would collide); the
# native capi keeps its own `tensor_rpc_inflight` twin counting ALL async
# tensor RPCs, windowed or not.

_pipeline_mu = threading.Lock()
_pipeline_inflight = 0

# Anchors for in-flight completion-notification trampolines: a future
# dropped mid-flight must not let GC free a CFUNCTYPE the native side is
# about to call. Fired notifications remove themselves; a canceled future
# whose notification never fires leaks one small object (rare, bounded by
# the caller's cancel rate). A list, not a set — ctypes function pointers
# are unhashable.
_live_done_cbs: list = []


def _pipeline_inflight_add(delta: int) -> None:
    global _pipeline_inflight
    with _pipeline_mu:
        _pipeline_inflight += delta


def _pipeline_gauge() -> None:
    from brpc_tpu.observability import metrics as obs

    obs.gauge("tensor_pipeline_inflight", lambda: _pipeline_inflight)


def _encode_meta(arr: np.ndarray) -> bytes:
    # Delegates to codec.pack_header — the ONE home of the '<I len + JSON'
    # header framing, so the raw and quantized wire cannot drift apart.
    from brpc_tpu.runtime import codec as codec_mod

    return codec_mod.pack_header({"dtype": arr.dtype.str,
                                  "shape": list(arr.shape)})


def pad_header64(header: bytes) -> bytes:
    """Pad a [u32 n|JSON] header with trailing spaces until its TOTAL
    length is a 64-byte multiple. One-sided publications use this so the
    payload that follows the header in the blob starts 64B-aligned:
    ``read_np`` aligns the BLOB start, and the CPU backend's zero-copy
    ``device_put`` alias check needs the DATA start aligned — without
    the pad, essentially every header length breaks the alias and
    re-adds the full-payload copy the owned-buffer path exists to
    remove. JSON parsers ignore the trailing whitespace."""
    pad = -len(header) % 64
    if pad == 0:
        return header
    body = header[4:] + b" " * pad
    return struct.pack("<I", len(body)) + body


def _decode_meta_ex(buf: bytes) -> Tuple[dict, bytes]:
    """Header -> (full metadata dict, rest of payload). The dict carries
    dtype/shape always, plus codec/block when the tensor rides the
    quantized wire format (brpc_tpu/runtime/codec.py)."""
    (n,) = struct.unpack_from("<I", buf)
    return json.loads(buf[4:4 + n].decode()), buf[4 + n:]


def _decode_meta(buf: bytes) -> Tuple[np.dtype, tuple, bytes]:
    meta, rest = _decode_meta_ex(buf)
    return np.dtype(meta["dtype"]), tuple(meta["shape"]), rest


class WireTensor:
    """A response tensor already encoded for the wire: ``data`` (a uint8
    ndarray staged into the service arena as-is) plus the exact metadata
    ``header`` prefix to send — the quantized pull path's way of handing
    the trampoline pre-built bytes instead of a host array (whose header
    the trampoline would synthesize as raw).

    ``placed`` is an optional ``(off, nbytes)`` range the handler already
    wrote into the SERVICE'S OWN arena (``PullQ`` assembles its group
    payload in place to skip the concat-then-place double memcpy); the
    trampoline sends that range as-is — with autofree, so the handler
    must not free it — instead of staging ``data``."""

    __slots__ = ("data", "header", "placed")

    def __init__(self, data: Optional[np.ndarray], header: bytes,
                 placed: Optional[Tuple[int, int]] = None):
        self.data = data
        self.header = header
        self.placed = placed


def _as_host_array(array) -> np.ndarray:
    """jax.Array -> host np.ndarray (one D2H DMA on TPU; zero-copy view on
    the CPU backend); np.ndarray passes through."""
    return np.asarray(array)


def _device_put_from_view(arr: np.ndarray, device):
    """``jax.device_put`` an array that VIEWS arena/view pages, safely.

    On a real accelerator this is the zero-copy discipline: the H2D DMA
    copies by definition, so the view can be released the moment
    ``block_until_ready`` returns. On the CPU backend, XLA ZERO-COPY
    ALIASES 64-byte-aligned host buffers — and arena ranges are 64B-
    aligned — so the "device" array would keep pointing into pages the
    release hands back for reuse. Detach with a host copy there first.
    """
    import jax

    target = device if device is not None else jax.devices()[0]
    if getattr(target, "platform", "cpu") == "cpu":
        arr = np.array(arr)
    dev = jax.device_put(arr, device)
    dev.block_until_ready()  # transfer completes before the view release
    return dev


class TensorArena:
    """Registered transfer memory, exposed to numpy/jax as views."""

    def __init__(self, nbytes: int):
        self._L = _bind_tensor_api(lib())
        self._h = self._L.tbrpc_arena_create(nbytes)
        if not self._h:
            raise MemoryError(f"arena create({nbytes}) failed")
        self._base = self._L.tbrpc_arena_base(self._h)
        self.nbytes = nbytes
        _metrics()  # occupancy gauges cover this arena from now on

    @property
    def handle(self) -> int:
        return self._h

    def alloc(self, nbytes: int) -> int:
        if not self._h:
            raise RuntimeError("arena is closed")
        off = self._L.tbrpc_arena_alloc(self._h, nbytes)
        if off < 0:
            raise MemoryError(f"arena alloc({nbytes}) failed (fragmented?)")
        return off

    def free(self, off: int) -> None:
        self._L.tbrpc_arena_free(self._h, off)

    def view(self, off: int, nbytes: int) -> np.ndarray:
        """A uint8 numpy view of arena pages — writes here ARE the staging
        transfer (no further copy before the wire)."""
        buf = (ctypes.c_uint8 * nbytes).from_address(self._base + off)
        return np.ctypeslib.as_array(buf)

    def place(self, array) -> Tuple[int, int, np.ndarray]:
        """Stage an array's bytes into the arena: (off, nbytes, host_copy).

        One D2H DMA for a TPU-resident jax.Array; a plain memcpy for host
        arrays. Returns the host ndarray too (carrying dtype/shape for the
        metadata header).
        """
        host = _as_host_array(array)
        if host.nbytes == 0:
            return 0, 0, host  # empty tensors ride as metadata only
        raw = host.reshape(-1).view(np.uint8)
        off = self.alloc(host.nbytes)
        self.view(off, host.nbytes)[:] = raw
        return off, host.nbytes, host

    def busy_bytes(self) -> int:
        if not self._h:
            return 0  # a closed arena holds nothing
        return self._L.tbrpc_arena_busy_bytes(self._h)

    def wait_reusable(self, off: int, timeout_ms: int = -1) -> bool:
        # Zero-timeout probe first: an actual PARK here means the data
        # plane is gated on reference drain (the wire release hasn't come
        # back) — the stall counter is the backpressure signal /tensorz
        # and dashboards watch.
        if self._L.tbrpc_arena_wait_reusable(self._h, off, 0) == 0:
            return True
        if timeout_ms == 0:
            return False
        _metrics()["wait_stalls"].add(1)
        return self._L.tbrpc_arena_wait_reusable(self._h, off,
                                                 timeout_ms) == 0

    def close(self) -> None:
        if self._h:
            self._L.tbrpc_arena_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class OnesideMiss(Exception):
    """A one-sided read that must fall back to the RPC path for this
    call: not published (status 1) or torn past the retry budget under a
    republish storm (status 2). Transient by contract."""

    def __init__(self, name: str, status: int):
        super().__init__(f"oneside read miss for {name!r} (status {status})")
        self.name = name
        self.status = status


class OnesideGone(OnesideMiss):
    """The mapped window is gone (destroyed window, swept reader claim):
    unmap and stop trying — the permanent-fallback signal."""


class OnesideWindow:
    """Publisher side of one-sided tensor reads: seqlock-stamped
    publication slots inside a :class:`TensorArena`, readable by any
    same-host process that mapped the arena's shm segment. ``publish``
    hands over a range the caller already wrote (the window retires and
    reclaims the displaced range via epoch-based reclamation — never
    under a reader mid-copy); ``own=False`` publishes in place without
    ever freeing (serving KV pages, whose ranges the session owns)."""

    def __init__(self, arena: TensorArena, n_slots: int = 256,
                 n_readers: int = 64):
        self._L = _bind_tensor_api(lib())
        self.arena = arena
        self._h = self._L.tbrpc_oneside_window_create(arena.handle, n_slots,
                                                      n_readers)
        if not self._h:
            raise MemoryError("oneside window create failed (arena full?)")

    def publish(self, name: str, off: int, nbytes: int, version: int,
                own: bool = True) -> None:
        if not self._h:
            raise RuntimeError("oneside window is closed")
        if self._L.tbrpc_oneside_publish(self._h, name.encode(), off,
                                         nbytes, version,
                                         1 if own else 0) != 0:
            raise ValueError(
                f"oneside publish({name!r}, off={off}, n={nbytes}) refused")

    def begin_rewrite(self, name: str) -> None:
        """Write-lock ``name`` (readers retry) while its payload is
        rewritten in place; the next ``publish`` commits."""
        if self._h:
            self._L.tbrpc_oneside_begin_rewrite(self._h, name.encode())

    def unpublish(self, name: str) -> bool:
        if not self._h:
            return False
        return self._L.tbrpc_oneside_unpublish(self._h, name.encode()) == 0

    def describe(self) -> dict:
        """The mapping-handshake descriptor a server hands to clients
        (over any ordinary RPC): shm name, size, directory offset and the
        random window token a reader validates after mapping."""
        if not self._h:
            raise RuntimeError("oneside window is closed")
        n = self._L.tbrpc_oneside_window_describe(self._h, None, 0)
        buf = ctypes.create_string_buffer(n + 1)
        self._L.tbrpc_oneside_window_describe(self._h, buf, n + 1)
        doc = json.loads(buf.value.decode())
        doc["token"] = int(doc["token"])  # shipped as a decimal string
        return doc

    def close(self) -> None:
        if self._h:
            self._L.tbrpc_oneside_window_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def oneside_stats() -> dict:
    """Process-wide one-sided counters + per-window reclamation state."""
    L = _bind_tensor_api(lib())
    n = L.tbrpc_oneside_stats_json(None, 0)
    buf = ctypes.create_string_buffer(n + 1)
    L.tbrpc_oneside_stats_json(buf, n + 1)
    return json.loads(buf.value.decode())


class OnesideReader:
    """Reader side: a same-host mapping of a peer's published window.
    ``read`` copies out one committed version under the reader's epoch
    pin (the publisher cannot reclaim the range mid-copy) and raises
    :class:`OnesideMiss`/:class:`OnesideGone` when the caller should use
    the RPC path instead."""

    def __init__(self, handle):
        self._L = _bind_tensor_api(lib())
        self._h = handle

    @classmethod
    def map(cls, desc: dict) -> Optional["OnesideReader"]:
        """Map from a window descriptor; None means stay on the RPC path
        (off-host shm name, stale token, full reader table)."""
        L = _bind_tensor_api(lib())
        try:
            h = L.tbrpc_oneside_map(str(desc["shm"]).encode(),
                                    int(desc["bytes"]),
                                    int(desc["dir_off"]),
                                    int(desc["token"]))
        except (KeyError, TypeError, ValueError):
            return None
        return cls(h) if h else None

    def read(self, name: str) -> Tuple[int, bytes]:
        """-> (version, payload bytes) of the committed publication."""
        version, arr = self.read_np(name)
        return version, arr.tobytes()

    def read_np(self, name: str) -> Tuple[int, np.ndarray]:
        """-> (version, OWNED uint8 ndarray): stat for the size, then ONE
        native memcpy straight into a 64B-aligned numpy buffer the
        caller owns — decode may view and even device_put-alias it with
        no reuse hazard (unlike arena pages, nothing ever rewrites this
        buffer). The large-tensor hot path: the bytes-returning ``read``
        costs one more copy."""
        if not self._h:
            raise OnesideGone(name, 3)
        nbytes = ctypes.c_uint64()
        version = ctypes.c_uint64()
        rc = self._L.tbrpc_oneside_stat(self._h, name.encode(),
                                        ctypes.byref(nbytes),
                                        ctypes.byref(version))
        # A republish between stat and read_into may grow the payload:
        # read_into answers TOO_SMALL (4) with the needed size — retry.
        for _ in range(8):
            if rc not in (0, 4):
                break
            need = nbytes.value
            # Over-allocate 64 bytes and slice to a 64B-aligned start so
            # the CPU backend's zero-copy device_put alias check passes.
            backing = np.empty(need + 64, np.uint8)
            shift = (-backing.ctypes.data) % 64
            arr = backing[shift:shift + need]
            rc = self._L.tbrpc_oneside_read_into(
                self._h, name.encode(), ctypes.c_void_p(backing.ctypes.data
                                                        + shift),
                need, ctypes.byref(nbytes), ctypes.byref(version))
            if rc == 0:
                return int(version.value), arr
        if rc == 3:
            raise OnesideGone(name, rc)
        raise OnesideMiss(name, rc)

    def close(self) -> None:
        if self._h:
            self._L.tbrpc_oneside_unmap(self._h)
            self._h = None

    unmap = close

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def consume_oneside_payload(payload, device=None,
                            note_name: Optional[str] = None,
                            to_host: bool = False):
    """Decode one one-sided payload — the same self-describing
    [u32 meta-len|meta JSON|bytes] framing the Pull RPC ships (raw or
    quantized), so the two paths CANNOT return different values for the
    same committed version (the fallback-parity contract). Returns a
    device array, or a detached host ndarray with ``to_host=True``.

    ``payload`` is either ``bytes`` or an OWNED uint8 ndarray
    (:meth:`OnesideReader.read_np`). The owned form is the large-tensor
    hot path: its buffer is never rewritten, so the raw branch may view
    it in place and let ``jax.device_put`` alias it on the CPU backend —
    the detach copy the arena-view path needs is pure waste here."""
    owned = isinstance(payload, np.ndarray)
    if owned:
        (n,) = struct.unpack("<I", payload[:4].tobytes())
        meta = json.loads(payload[4:4 + n].tobytes().decode())
        u8 = payload[4 + n:]
    else:
        meta, rest = _decode_meta_ex(payload)
        u8 = np.frombuffer(rest, dtype=np.uint8)
    if "codec" in meta:
        from brpc_tpu.runtime import codec as codec_mod

        if note_name is not None:
            nbytes = int(np.prod(meta["shape"], dtype=np.int64)
                         ) * np.dtype(meta["dtype"]).itemsize
            codec_mod.note(note_name, meta["codec"], nbytes, int(u8.nbytes))
        with _stage("dequant"):
            if to_host:
                return codec_mod.decode(meta, u8)
            return _dequant_put_from_view(meta, u8, device, codec_mod)
    arr = u8.view(np.dtype(meta["dtype"])).reshape(tuple(meta["shape"])) \
        if owned else np.frombuffer(
            u8, dtype=np.dtype(meta["dtype"])).reshape(tuple(meta["shape"]))
    if to_host:
        return arr if owned else np.array(arr)
    with _stage("device_put"):
        if owned:
            # Alias-safe: the caller-owned buffer outlives the jax array
            # (device_put keeps a reference) and is never rewritten.
            import jax

            return jax.device_put(arr, device)
        # `bytes` payloads are read-only frombuffer views — the helper's
        # detach discipline covers them.
        return _device_put_from_view(arr, device)


class TensorView:
    """A zero-copy window onto a received tensor (the peer's arena pages or
    the connection's RX segment). ``release()`` is what sends the release
    frame back and lets the sender reuse the range — call it (or use as a
    context manager) as soon as the bytes are consumed (e.g. after
    jax.device_put returns)."""

    def __init__(self, L, view_handle, ptr, nbytes, copied: bool):
        self._L = L
        self._view = view_handle
        self._ptr = ptr
        self._copied = copied
        self.nbytes = nbytes

    def ndarray(self) -> np.ndarray:
        if not self.nbytes or not self._ptr:
            # Zero-size tensors ride as metadata only — there is no
            # attachment, so the view holds no pages (_ptr is None).
            return np.empty(0, dtype=np.uint8)
        buf = (ctypes.c_uint8 * self.nbytes).from_address(self._ptr)
        return np.ctypeslib.as_array(buf)

    @property
    def zero_copy(self) -> bool:
        return not self._copied

    def release(self) -> None:
        if self._view:
            self._L.tbrpc_view_free(self._view)
            self._view = None
        elif self._copied and self._ptr:
            self._L.tbrpc_free(self._ptr)
        self._ptr = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()

    def __del__(self):
        try:
            self.release()
        except Exception:  # noqa: BLE001
            pass


def consume_pull_reply(payload: bytes, view: "TensorView", device=None,
                       note_name: Optional[str] = None):
    """Decode a pulled-tensor reply and device_put it straight from the
    zero-copy view, releasing the view once the transfer completed.
    Returns ``(rest_of_payload, jax.Array, logical_nbytes)``.

    ONE implementation for the sync ``pull_device`` and the pipelined
    consumers (``ParameterClient.pull_all``'s on_reply) so the decode path
    and its aliasing discipline cannot drift apart. Responses are
    self-describing: a header carrying codec/block fields takes the
    dequantize path (fused into the device_put — on TPU the H2D DMA moves
    the ~4x smaller codes and the Pallas kernel widens on-chip; elsewhere
    the numpy dequant IS the detach copy, so nothing is copied twice);
    without codec fields this is byte-for-byte the raw path.
    """
    with view:
        meta, rest = _decode_meta_ex(payload)
        if "codec" in meta:
            from brpc_tpu.runtime import codec as codec_mod

            nbytes = int(np.prod(meta["shape"], dtype=np.int64)
                         ) * np.dtype(meta["dtype"]).itemsize
            if note_name is not None:
                # Decode side of the tensor_codec_* accounting contract:
                # a pull-only trainer must still show its logical/wire
                # bytes and ratio on /vars and /tensorz.
                codec_mod.note(note_name, meta["codec"], nbytes,
                               int(view.nbytes))
            with _stage("dequant"):
                try:
                    dev = _dequant_put_from_view(meta, view.ndarray(),
                                                 device, codec_mod)
                except ValueError as ve:
                    # Corrupt/truncated quantized reply (size mismatch,
                    # unknown codec, missing ml_dtypes): surface as the
                    # structural app code so pull_all's PartialPullError
                    # salvage and the fleet's per-name re-route engage —
                    # a bare ValueError would bypass both and discard
                    # every already-decoded groupmate.
                    raise RpcError(
                        E_UNDECODABLE,
                        f"undecodable tensor payload: {ve}") from ve
        else:
            arr = np.frombuffer(
                view.ndarray(), dtype=np.dtype(meta["dtype"])).reshape(
                    tuple(meta["shape"]))
            nbytes = view.nbytes
            with _stage("device_put"):
                dev = _device_put_from_view(arr, device)
    return rest, dev, nbytes


def _detach_device_put_batch(parts, device):
    """ONE ``jax.device_put`` over every (codes, scales) pair in ``parts``
    and ONE completion barrier BEFORE the caller releases the arena pages
    those buffers alias — the quantized wire's view-aliasing discipline
    lives here and nowhere else (single-tensor callers pass one pair;
    ``pull_all``'s group path amortizes the ~0.1-0.4ms per-put dispatch
    across the whole group). Mirrors ``_device_put_from_view``'s CPU
    hazard: XLA zero-copy aliases 64B-aligned host buffers, so a CPU
    target detaches with a host copy first. Returns the flat
    ``[q0, s0, q1, s1, ...]`` device list."""
    import jax

    target = device if device is not None else jax.devices()[0]
    flat = []
    for q, s in parts:
        flat.extend((q, s))
    if getattr(target, "platform", "cpu") == "cpu":
        flat = [np.array(a) for a in flat]
    devs = jax.device_put(flat, device)
    jax.block_until_ready(devs)
    return devs


def _dequant_widen(q_dev, s_dev, block, n, shape, want=None):
    """Widen-and-scale already-detached codes/scales on device (Pallas on
    TPU, the jnp reference elsewhere — ``dequantize_blocks`` auto-routes
    like ``fused_momentum_update``). The output is a FRESH buffer, so no
    further blocking; ``want`` restores a non-fp32 logical dtype."""
    from brpc_tpu.ops.quantize import dequantize_blocks

    out = dequantize_blocks(q_dev, s_dev, block=int(block), n=int(n),
                            shape=tuple(shape))
    if want is not None and np.dtype(want) != np.float32:
        out = out.astype(np.dtype(want))
    return out


def _dequant_put_from_view(meta: dict, payload_u8: np.ndarray, device,
                           codec_mod):
    """Dequantize a received ``[scales][codes]`` view into a device array.

    TPU: device_put the codes + scales (the H2D DMA detaches them from
    the arena pages by definition) and run the Pallas widen-and-scale
    kernel on-chip (brpc_tpu/ops/quantize.py — auto-routed like
    fused_momentum_update). Elsewhere: the numpy dequant writes a fresh
    fp32 buffer — detached by construction, so device_put may alias it
    safely (unlike raw views, which need an explicit detach copy).
    """
    import jax

    target = device if device is not None else jax.devices()[0]
    if getattr(target, "platform", "cpu") != "cpu":
        q, scales = codec_mod.split_wire(meta, payload_u8)
        q_dev, s_dev = _detach_device_put_batch([(q, scales)], device)
        return _dequant_widen(q_dev, s_dev, meta["block"],
                              int(np.prod(meta["shape"], dtype=np.int64)),
                              meta["shape"], want=meta["dtype"])
    host = codec_mod.decode(meta, payload_u8)  # fresh buffer: no alias risk
    dev = jax.device_put(host, device)
    dev.block_until_ready()
    return dev


class TensorFuture:
    """One in-flight async tensor RPC (``TensorChannel.call_async``).

    ``result()`` parks the calling thread until the response arrives and
    returns ``(payload, TensorView)`` — the exact ownership contract of
    the sync ``call_raw`` (release the view once the bytes are consumed).
    Results are cached on first take, so repeated ``result()`` calls
    return the same objects, and the future stays valid after its channel
    closes (the native controller owns everything it needs).

    ``cancel()`` ends an in-flight RPC with ECANCELED; ``close()`` (or
    GC) on a never-waited future cancels it and lets the native side
    release the response exactly once, whichever way the race goes.
    """

    def __init__(self, L, handle, service_method, done_cb=None):
        self._L = L
        self._h = handle
        self._method = service_method
        self._cb = done_cb  # the ctypes trampoline must outlive the RPC
        self._payload = None
        self._view: Optional[TensorView] = None
        self._error: Optional[RpcError] = None
        self._taken = False

    def done(self) -> bool:
        """Non-blocking completion probe (moves a ready native result
        into the Python-side cache)."""
        return self._taken or self._poll(0)

    def result(self, timeout_ms: int = -1) -> Tuple[bytes, TensorView]:
        """Wait for completion -> (payload, view). ``timeout_ms >= 0``
        raises TimeoutError if still in flight (retry later); RPC
        failures raise RpcError."""
        if not self._taken and not self._poll(timeout_ms):
            raise TimeoutError(
                f"{self._method}: still in flight after {timeout_ms}ms")
        if self._error is not None:
            raise self._error
        return self._payload, self._view

    def _poll(self, timeout_ms: int) -> bool:
        if not self._h:
            raise RuntimeError("future is closed")
        L = self._L
        resp = ctypes.c_void_p()
        resp_len = ctypes.c_size_t()
        view = ctypes.c_void_p()
        ratt = ctypes.c_void_p()
        ratt_len = ctypes.c_size_t()
        copied = ctypes.c_int()
        errbuf = ctypes.create_string_buffer(256)
        outs = (ctypes.byref(resp), ctypes.byref(resp_len),
                ctypes.byref(view), ctypes.byref(ratt),
                ctypes.byref(ratt_len), ctypes.byref(copied),
                errbuf, len(errbuf))
        if timeout_ms < 0:
            rc = L.tbrpc_future_wait(self._h, *outs)
        else:
            rc = L.tbrpc_future_timed_wait(self._h, timeout_ms, *outs)
            if rc == -1:
                return False  # still in flight; nothing consumed
        self._taken = True
        if rc != 0:
            self._error = RpcError(rc, errbuf.value.decode(errors="replace"))
        else:
            try:
                self._payload = (ctypes.string_at(resp, resp_len.value)
                                 if resp_len.value else b"")
            finally:
                L.tbrpc_free(resp)
            self._view = TensorView(L, view.value, ratt.value,
                                    ratt_len.value, bool(copied.value))
        self.close()  # ownership is out; the native box is spent
        return True

    def cancel(self) -> None:
        """Cancel an in-flight RPC (later ``result()`` raises RpcError
        ECANCELED); a completed-but-unconsumed response is released now,
        exactly once. No-op once the result was taken."""
        if self._h and not self._taken:
            self._L.tbrpc_future_cancel(self._h)

    def close(self) -> None:
        """Release the native future (idempotent). In flight: cancels;
        the completion path frees the response."""
        if self._h:
            self._L.tbrpc_future_destroy(self._h)
            self._h = None
            # The notification trampoline unanchors ITSELF when it fires
            # (_notify); dropping our ref here is enough — a close that
            # races an unfired notification leaves the anchor in place.
            self._cb = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class PipelineWindow:
    """Bounded-window pipelining over one ``TensorChannel``.

    Keeps up to ``window`` tensor RPCs in flight, overlapping the arena
    staging (D2H DMA + memcpy) of tensor k+1 with the wire transfer of
    tensors k, k-1, ... Submission order == delivery order: a full window
    completes the OLDEST call before staging the next, and each range is
    freed as its RPC completes — so the arena holds at most ``window``
    staged chunks (window x chunk bytes) at any moment, double-buffered
    against the wire.

    Results are handed to ``on_reply(tag, payload, view)`` in submit
    order on the submitting thread (release the view as soon as the bytes
    are consumed), or — without ``on_reply`` — collected by ``flush()``
    as ``[(tag, payload, view), ...]``.

    Observability: submissions ride the process-wide
    ``tensor_pipeline_inflight`` gauge, and the staging/drain phases
    annotate the active rpcz span as ``arena_stage`` / ``wire_wait``.
    """

    def __init__(self, channel: "TensorChannel", window: int = 4,
                 on_reply: Optional[Callable] = None):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.channel = channel
        self.window = window
        self.on_reply = on_reply
        self._q: deque = deque()  # (tag, future, arena_off, arena_len)
        self._results: list = []
        _pipeline_gauge()

    def inflight(self) -> int:
        return len(self._q)

    def complete_one(self) -> bool:
        """Drain the OLDEST in-flight call only (delivering its reply
        through ``on_reply`` / the collected results); ``False`` when
        nothing is in flight. The overlapped step driver's per-tensor
        confirm point: ``opt-k`` drains exactly until push k's reply
        lands instead of flushing the whole window (which would serialize
        every later push behind the first confirm). A failure carries the
        failed call's tag as ``e.pipeline_tag`` so the caller can
        attribute it per tensor (partial-salvage bookkeeping)."""
        if not self._q:
            return False
        self._complete_oldest()
        return True

    def submit(self, service_method: str, array=None, request: bytes = b"",
               tag=None, encoder=None) -> None:
        """Stage ``array`` (optional) into the channel arena and start
        the RPC; blocks only while the window is full (draining the
        oldest in-flight call first).

        ``encoder(host) -> (wire_uint8, header_bytes) | None`` (optional)
        runs at arena-stage time — quantization overlaps the wire exactly
        like the staging copy already does (codes of tensor k+1 are being
        computed while tensor k's bytes fly). ``None`` means this tensor
        rides raw (the per-call degrade)."""
        while len(self._q) >= self.window:
            self._complete_oldest()
        off = length = 0
        if array is not None:
            with _stage("arena_stage"):
                enc = None
                if encoder is not None:
                    host = _as_host_array(array)
                    enc = encoder(host)
                    array = host
                if enc is None:
                    off, length, host = self.channel.place_with_meta(array)
                    request = _encode_meta(host) + request
                else:
                    wire, header = enc
                    off, length, _ = self.channel.arena.place(wire)
                    request = header + request
        try:
            fut = self.channel.call_async(service_method, request, off,
                                          length)
        except Exception:
            # Not in _q yet, so abort()/flush() would never free it — a
            # caller surviving transient submit failures must not leak one
            # staged chunk per retry.
            if length:
                self.channel.arena.free(off)
            raise
        _pipeline_inflight_add(1)
        self._q.append((tag, fut, off, length))

    def _complete_oldest(self) -> None:
        # EVERY drain point annotates failures with the failed call's
        # tag (``e.pipeline_tag``) — not just complete_one: submit's
        # window-full drain and flush() surface the same errors, and
        # per-tag salvage/retry layers (the step driver, PushQ's
        # rollback check, the collectives' shed redelivery) must be
        # able to attribute those too.
        tag, fut, off, length = self._q.popleft()
        try:
            try:
                with _stage("wire_wait"):
                    payload, view = fut.result()
            finally:
                _pipeline_inflight_add(-1)
                if length:
                    self.channel.arena.free(off)  # freed as refs drain
            if self.on_reply is not None:
                try:
                    self.on_reply(tag, payload, view)
                except Exception:
                    # The view was handed out but is in neither _q nor
                    # _results: release here or the PEER's range never
                    # drains (release() is idempotent).
                    view.release()
                    raise
            else:
                self._results.append((tag, payload, view))
        except Exception as e:  # noqa: BLE001 — annotate and re-raise
            try:
                e.pipeline_tag = tag
            except Exception:  # noqa: BLE001 — exotic immutable exception
                pass
            raise

    def flush(self) -> list:
        """Drain the window; returns (and clears) collected results when
        no ``on_reply`` consumer was given."""
        while self._q:
            self._complete_oldest()
        out, self._results = self._results, []
        return out

    def abort(self) -> None:
        """Error-path teardown: cancel and release everything in flight
        and every undelivered collected result."""
        while self._q:
            _tag, fut, off, length = self._q.popleft()
            _pipeline_inflight_add(-1)
            try:
                fut.cancel()
                fut.close()
            finally:
                if length:
                    self.channel.arena.free(off)
        for _tag, _payload, view in self._results:
            try:
                view.release()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        self._results = []

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *_exc):
        if exc_type is None:
            self.flush()
        else:
            self.abort()


class TensorChannel:
    """Client stub for tensor traffic: a ``tpu://`` channel plus a local
    arena the outbound tensors stage through."""

    def __init__(self, addr: str, arena: Optional[TensorArena] = None,
                 timeout_ms: int = 20000, max_retry: int = 0):
        self._L = _bind_tensor_api(lib())
        if not addr.startswith("tpu://") and "://" not in addr:
            addr = "tpu://" + addr
        self._h = self._L.tbrpc_channel_create(addr.encode(), timeout_ms,
                                               max_retry)
        if not self._h:
            raise RuntimeError(f"tensor channel init to {addr} failed")
        native._LIVE_CHANNELS.add(self)  # atexit teardown hygiene
        self.arena = arena if arena is not None else TensorArena(256 << 20)

    def call_raw(self, service_method: str, request: bytes,
                 att_off: int = 0, att_len: int = 0
                 ) -> Tuple[bytes, TensorView]:
        """One RPC: request bytes + an arena range as the attachment.
        Returns (response payload, response-attachment view)."""
        if not self._h:
            # NULL through ctypes would be a native deref, not an error.
            raise RuntimeError("tensor channel is closed")
        L = self._L
        resp = ctypes.c_void_p()
        resp_len = ctypes.c_size_t()
        view = ctypes.c_void_p()
        ratt = ctypes.c_void_p()
        ratt_len = ctypes.c_size_t()
        copied = ctypes.c_int()
        errbuf = ctypes.create_string_buffer(256)
        rc = L.tbrpc_call_tensor(
            self._h, service_method.encode(), request, len(request),
            self.arena.handle if att_len else None, att_off, att_len,
            ctypes.byref(resp), ctypes.byref(resp_len), ctypes.byref(view),
            ctypes.byref(ratt), ctypes.byref(ratt_len), ctypes.byref(copied),
            errbuf, len(errbuf))
        if rc != 0:
            raise RpcError(rc, errbuf.value.decode(errors="replace"))
        try:
            payload = (ctypes.string_at(resp, resp_len.value)
                       if resp_len.value else b"")
        finally:
            L.tbrpc_free(resp)
        return payload, TensorView(L, view.value, ratt.value, ratt_len.value,
                                   bool(copied.value))

    def call_async(self, service_method: str, request: bytes = b"",
                   att_off: int = 0, att_len: int = 0,
                   on_done: Optional[Callable[[int], None]] = None
                   ) -> TensorFuture:
        """Submit one RPC without blocking: ``call_raw``'s async twin,
        returning a :class:`TensorFuture`. The arena range (if any) takes
        its local reference before this returns, so ``arena.free`` any
        time after submission is safe (deferred-free semantics).

        ``on_done(status)`` (optional) fires on a callback-pool pthread
        before the future becomes waitable — a light notification hook
        (wake an event loop); consume results via ``future.result()``,
        never inside the hook."""
        if not self._h:
            raise RuntimeError("tensor channel is closed")
        L = self._L
        cb = ctypes.cast(None, _TENSOR_DONE_CB)  # NULL fn ptr: no hook
        if on_done is not None:
            def _notify(_ctx, status, *_rest):
                try:
                    on_done(status)
                except Exception:  # noqa: BLE001 — a notification hook
                    pass           # must not unwind into the pool thread
                finally:
                    try:
                        _live_done_cbs.remove(cb)
                    except ValueError:
                        pass

            cb = _TENSOR_DONE_CB(_notify)
            _live_done_cbs.append(cb)
        h = L.tbrpc_call_tensor_async(
            self._h, service_method.encode(), request, len(request),
            self.arena.handle if att_len else None, att_off, att_len,
            cb, None)
        if not h:
            raise RpcError(native.TRPC_EINTERNAL,
                           f"async submit of {service_method} failed")
        return TensorFuture(L, h, service_method, done_cb=cb)

    def call(self, service_method: str, array=None, request: bytes = b""
             ) -> Tuple[bytes, Optional[np.ndarray]]:
        """Send a tensor (or nothing), receive a tensor (or nothing).

        The outbound array stages into the local arena (freed after the
        wire release returns); the inbound one is device_put-able — it is
        materialized as an ndarray COPY here only if the caller keeps it,
        via pull() below for the zero-copy discipline.
        """
        off = length = 0
        if array is not None:
            off, length, host = self.place_with_meta(array)
            request = _encode_meta(host) + request
        try:
            payload, view = self.call_raw(service_method, request, off,
                                          length)
        finally:
            if length:
                self.arena.free(off)  # deferred until releases drain
        with view:
            if view.nbytes == 0:
                try:  # an empty tensor still carries its metadata header
                    dtype, shape, rest = _decode_meta(payload)
                    return rest, np.empty(shape, dtype=dtype)
                except Exception:  # noqa: BLE001 — tensor-less response
                    return payload, None
            meta, rest = _decode_meta_ex(payload)
            if "codec" in meta:  # self-describing quantized response
                from brpc_tpu.runtime import codec as codec_mod

                return rest, codec_mod.decode(meta, view.ndarray())
            arr = np.frombuffer(
                view.ndarray(), dtype=np.dtype(meta["dtype"])).reshape(
                    tuple(meta["shape"]))
            return rest, np.array(arr)  # detach before releasing the view

    def place_with_meta(self, array) -> Tuple[int, int, np.ndarray]:
        return self.arena.place(array)

    def pull_device(self, service_method: str, request: bytes = b"",
                    device=None, note_name: Optional[str] = None):
        """Fetch a tensor and jax.device_put it STRAIGHT from the received
        view (H2D DMA from the shared pages; no intermediate host copy),
        then release the view. Returns (rest_of_payload, jax.Array).

        Observability: records into the tensor_pull LatencyRecorder and
        tensor_pull_bytes counter, and annotates the active rpcz span with
        the rpc / device_put stage split."""
        t0 = time.monotonic()
        with _stage("rpc"):
            payload, view = self.call_raw(service_method, request)
        rest, dev, nbytes = consume_pull_reply(payload, view, device,
                                               note_name=note_name)
        m = _metrics()
        m["pull"].record_s(time.monotonic() - t0)
        m["pull_bytes"].add(nbytes)
        return rest, dev

    def push_device(self, service_method: str, array,
                    request: bytes = b"", encoder=None) -> bytes:
        """Send a device array (D2H into the arena, by-reference on the
        wire); waits for the wire release so the arena cannot fill up under
        a streaming push loop. Returns the response payload.

        ``encoder`` is the same per-tensor hook ``PipelineWindow.submit``
        takes: ``(wire_uint8, header_bytes) | None`` computed at
        arena-stage time; None rides raw.

        Observability: records into the tensor_push LatencyRecorder and
        tensor_push_bytes counter, and annotates the active rpcz span with
        the arena_stage (D2H + staging copy) / rpc stage split."""
        t0 = time.monotonic()
        with _stage("arena_stage"):
            host = _as_host_array(array)
            enc = encoder(host) if encoder is not None else None
            if enc is None:
                off, length, host = self.place_with_meta(host)
                header = _encode_meta(host)
            else:
                wire, header = enc
                off, length, _ = self.arena.place(wire)
        try:
            with _stage("rpc"):
                payload, view = self.call_raw(
                    service_method, header + request, off, length)
            view.release()
            m = _metrics()
            m["push"].record_s(time.monotonic() - t0)
            m["push_bytes"].add(length)
            return payload
        finally:
            if length:
                self.arena.free(off)

    def close(self) -> None:
        if self._h:
            self._L.tbrpc_channel_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


# Handler: (method, request_bytes, attachment_view: np.ndarray|None)
#   -> (response_bytes, response_array_or_None)
TensorHandler = Callable[[str, bytes, Optional[np.ndarray]],
                         Tuple[bytes, Optional[object]]]


def add_tensor_service(server: native.Server, name: str,
                       handler: TensorHandler,
                       arena: Optional[TensorArena] = None) -> TensorArena:
    """Host a tensor service on a native Server: the handler reads request
    tensors IN PLACE (a numpy view of the sender's pages) and returns
    response tensors through the service's own arena (by-reference on the
    wire). Returns that arena."""
    L = _bind_tensor_api(lib())
    srv_arena = arena if arena is not None else TensorArena(256 << 20)

    def trampoline(ctx, method, req, req_len, att, att_len,
                   resp, resp_len, resp_arena, resp_off, resp_att_len,
                   resp_autofree, error_code, err_text, err_text_cap):
        t0 = time.monotonic()
        try:
            request = ctypes.string_at(req, req_len) if req_len else b""
            att_view = None
            if att_len:
                buf = (ctypes.c_uint8 * att_len).from_address(att)
                att_view = np.ctypeslib.as_array(buf)
                if request[:4] and len(request) >= 4:
                    # Typed sends prefix the payload with dtype/shape meta:
                    # give the handler a shaped view of the pages in place.
                    meta = None
                    try:
                        meta, request = _decode_meta_ex(request)
                    except Exception:  # noqa: BLE001 — raw-byte sender
                        pass
                    # Once a meta header DID decode (request is already
                    # header-stripped), a failure to apply it is a
                    # malformed/undecodable typed send — answer a clean
                    # RPC error, never hand the handler the flat wire
                    # bytes as if they were the tensor.
                    if meta is not None:
                        try:
                            if "codec" in meta:
                                # Quantized send: hand the handler the
                                # typed zero-copy window (codes + scales
                                # in place); dequantize() detaches when
                                # it consumes.
                                from brpc_tpu.runtime import (
                                    codec as codec_mod)

                                att_view = codec_mod.QuantizedView(
                                    meta, att_view)
                            else:
                                att_view = att_view.view(
                                    np.dtype(meta["dtype"])).reshape(
                                        tuple(meta["shape"]))
                        except Exception as e:  # noqa: BLE001
                            raise RpcError(
                                E_UNDECODABLE,
                                f"undecodable tensor payload "
                                f"(meta={meta!r}): {e}") from e
            r, out_arr = handler(method.decode(), request, att_view)
            if isinstance(out_arr, WireTensor):
                # Pre-encoded response (quantized pull path): stage the
                # wire bytes as-is, send the handler's exact header.
                if out_arr.placed is not None:
                    off, nbytes = out_arr.placed
                else:
                    off, nbytes, _ = srv_arena.place(out_arr.data)
                r = out_arr.header + r
                if nbytes:
                    resp_arena[0] = srv_arena.handle
                    resp_off[0] = off
                    resp_att_len[0] = nbytes
                    resp_autofree[0] = 1
            elif out_arr is not None:
                off, nbytes, host = srv_arena.place(out_arr)
                r = _encode_meta(host) + r
                if nbytes:
                    resp_arena[0] = srv_arena.handle
                    resp_off[0] = off
                    resp_att_len[0] = nbytes
                    # Autofree: the C side frees AFTER taking the response
                    # ref, so the range returns once the client releases.
                    resp_autofree[0] = 1
            if r:
                buf = L.tbrpc_alloc(len(r))
                ctypes.memmove(buf, r, len(r))
                resp[0] = buf
                resp_len[0] = len(r)
        except RpcError as e:
            error_code[0] = e.code if e.code != 0 \
                else native.TRPC_EINTERNAL
            fill_err_text(err_text, err_text_cap, e.text)
        except Exception as e:  # noqa: BLE001 — handler bug => EINTERNAL
            error_code[0] = native.TRPC_EINTERNAL
            fill_err_text(err_text, err_text_cap, f"{type(e).__name__}: {e}")
        finally:
            # Handler + response staging: what the client's tensor_pull
            # would otherwise misattribute to the network.
            _metrics()["serve"].record_s(time.monotonic() - t0)

    cb = _TENSOR_CB(trampoline)
    server._cbs.append(cb)  # keep alive alongside byte-service callbacks
    if L.tbrpc_server_add_tensor_service(
            server._h, name.encode(), cb, None) != 0:
        raise RuntimeError(f"add_tensor_service({name}) failed")
    return srv_arena
