"""Tensor-on-the-wire: jax.Array payloads riding the RPC framework.

This is the Python face of the native TensorArena bridge
(native/ttpu/tensor_arena.h — the tpu-native analog of the reference's
RDMA memory registration, rdma_helper.h:48): a shm-backed arena both ends
of a ``tpu://`` connection map. The flow per tensor:

  device array --(one D2H DMA)--> arena pages --(by-reference doorbell)-->
  receiver reads the SAME physical pages in place --(jax.device_put)-->
  device array on the other side.

No host-side copies happen between the arena and the receiving handler:
the IOBuf blocks on both sides point into the shared mapping (pointer
identity is asserted by native/test/test_tensor_arena.cpp). The staging
copy INTO the arena is the registered-memory discipline the reference's
RDMA path uses too (app data lands in registered blocks before the NIC
sees it); on a real pod the arena plays the pinned-host staging buffer
role that libtpu DMAs from.

Typed tensors ride as: request/response payload = a tiny metadata header
(dtype/shape, msgpack-free manual encoding), attachment = the raw bytes in
the arena.
"""

from __future__ import annotations

import ctypes
import json
import struct
import time
from typing import Callable, Optional, Tuple

import numpy as np

from brpc_tpu.runtime import native
from brpc_tpu.runtime.native import RpcError, fill_err_text, lib


def _bind_tensor_api(L: ctypes.CDLL) -> ctypes.CDLL:
    if getattr(L, "_tensor_api_bound", False):
        return L
    L.tbrpc_arena_create.restype = ctypes.c_void_p
    L.tbrpc_arena_create.argtypes = [ctypes.c_size_t]
    L.tbrpc_arena_destroy.argtypes = [ctypes.c_void_p]
    L.tbrpc_arena_base.restype = ctypes.c_void_p
    L.tbrpc_arena_base.argtypes = [ctypes.c_void_p]
    L.tbrpc_arena_alloc.restype = ctypes.c_int64
    L.tbrpc_arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    L.tbrpc_arena_free.restype = ctypes.c_int
    L.tbrpc_arena_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    L.tbrpc_arena_busy_bytes.restype = ctypes.c_int64
    L.tbrpc_arena_busy_bytes.argtypes = [ctypes.c_void_p]
    L.tbrpc_arenas_busy_bytes.restype = ctypes.c_int64
    L.tbrpc_arenas_busy_bytes.argtypes = []
    L.tbrpc_arenas_total_bytes.restype = ctypes.c_int64
    L.tbrpc_arenas_total_bytes.argtypes = []
    L.tbrpc_var_arena_gauges_create.argtypes = []
    L.tbrpc_arena_wait_reusable.restype = ctypes.c_int
    L.tbrpc_arena_wait_reusable.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64]
    L.tbrpc_call_tensor.restype = ctypes.c_int
    L.tbrpc_call_tensor.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_int),
        ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_view_free.argtypes = [ctypes.c_void_p]
    L.tbrpc_server_add_tensor_service.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, _TENSOR_CB, ctypes.c_void_p]
    L._tensor_api_bound = True
    return L


_TENSOR_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_void_p,                    # ctx
    ctypes.c_char_p,                    # method
    ctypes.c_void_p, ctypes.c_size_t,   # req
    ctypes.c_void_p, ctypes.c_size_t,   # attachment, IN PLACE
    ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),  # resp
    ctypes.POINTER(ctypes.c_void_p),    # resp_arena
    ctypes.POINTER(ctypes.c_uint64),    # resp_att_off
    ctypes.POINTER(ctypes.c_size_t),    # resp_att_len
    ctypes.POINTER(ctypes.c_int),       # resp_att_autofree
    ctypes.POINTER(ctypes.c_int),       # error_code
    ctypes.c_void_p, ctypes.c_size_t,   # err_text buffer (C-owned)
)


# ---- data-plane metrics (brpc_tpu/observability) ----
# Created lazily on first use: importing this module must not load the
# native library. One process-wide set — every channel/arena feeds the
# same recorders, mirroring how the native side aggregates per-method.

_metrics_cache = None


def _metrics():
    global _metrics_cache
    if _metrics_cache is None:
        from brpc_tpu.observability import metrics as obs

        L = _bind_tensor_api(lib())
        # Arena occupancy gauges (tensor_arena_busy_bytes/_total_bytes)
        # are NATIVE PassiveStatus vars over every live arena — created
        # through the capi but evaluated entirely in C++, so scrapes pay
        # no callback-pool hop or GIL, and a closing arena can't race the
        # walk. They ride /vars + /brpc_metrics + /tensorz like the rest.
        L.tbrpc_var_arena_gauges_create()
        _metrics_cache = {
            "pull": obs.latency("tensor_pull"),
            "push": obs.latency("tensor_push"),
            "pull_bytes": obs.counter("tensor_pull_bytes"),
            "push_bytes": obs.counter("tensor_push_bytes"),
            "wait_stalls": obs.counter("tensor_arena_wait_stalls"),
            # Server-side complement of the client recorders: the FULL
            # per-request cost of a Python tensor service — handler body
            # PLUS response staging into the arena (which happens after
            # the handler returns, so per-service recorders can't see it).
            "serve": obs.latency("tensor_handler"),
        }
    return _metrics_cache


def _stage(name):
    from brpc_tpu.observability import tracing

    return tracing.stage(name)


def _encode_meta(arr: np.ndarray) -> bytes:
    meta = json.dumps({"dtype": arr.dtype.str, "shape": list(arr.shape)})
    return struct.pack("<I", len(meta)) + meta.encode()


def _decode_meta(buf: bytes) -> Tuple[np.dtype, tuple, bytes]:
    (n,) = struct.unpack_from("<I", buf)
    meta = json.loads(buf[4:4 + n].decode())
    return np.dtype(meta["dtype"]), tuple(meta["shape"]), buf[4 + n:]


def _as_host_array(array) -> np.ndarray:
    """jax.Array -> host np.ndarray (one D2H DMA on TPU; zero-copy view on
    the CPU backend); np.ndarray passes through."""
    return np.asarray(array)


class TensorArena:
    """Registered transfer memory, exposed to numpy/jax as views."""

    def __init__(self, nbytes: int):
        self._L = _bind_tensor_api(lib())
        self._h = self._L.tbrpc_arena_create(nbytes)
        if not self._h:
            raise MemoryError(f"arena create({nbytes}) failed")
        self._base = self._L.tbrpc_arena_base(self._h)
        self.nbytes = nbytes
        _metrics()  # occupancy gauges cover this arena from now on

    @property
    def handle(self) -> int:
        return self._h

    def alloc(self, nbytes: int) -> int:
        if not self._h:
            raise RuntimeError("arena is closed")
        off = self._L.tbrpc_arena_alloc(self._h, nbytes)
        if off < 0:
            raise MemoryError(f"arena alloc({nbytes}) failed (fragmented?)")
        return off

    def free(self, off: int) -> None:
        self._L.tbrpc_arena_free(self._h, off)

    def view(self, off: int, nbytes: int) -> np.ndarray:
        """A uint8 numpy view of arena pages — writes here ARE the staging
        transfer (no further copy before the wire)."""
        buf = (ctypes.c_uint8 * nbytes).from_address(self._base + off)
        return np.ctypeslib.as_array(buf)

    def place(self, array) -> Tuple[int, int, np.ndarray]:
        """Stage an array's bytes into the arena: (off, nbytes, host_copy).

        One D2H DMA for a TPU-resident jax.Array; a plain memcpy for host
        arrays. Returns the host ndarray too (carrying dtype/shape for the
        metadata header).
        """
        host = _as_host_array(array)
        if host.nbytes == 0:
            return 0, 0, host  # empty tensors ride as metadata only
        raw = host.reshape(-1).view(np.uint8)
        off = self.alloc(host.nbytes)
        self.view(off, host.nbytes)[:] = raw
        return off, host.nbytes, host

    def busy_bytes(self) -> int:
        if not self._h:
            return 0  # a closed arena holds nothing
        return self._L.tbrpc_arena_busy_bytes(self._h)

    def wait_reusable(self, off: int, timeout_ms: int = -1) -> bool:
        # Zero-timeout probe first: an actual PARK here means the data
        # plane is gated on reference drain (the wire release hasn't come
        # back) — the stall counter is the backpressure signal /tensorz
        # and dashboards watch.
        if self._L.tbrpc_arena_wait_reusable(self._h, off, 0) == 0:
            return True
        if timeout_ms == 0:
            return False
        _metrics()["wait_stalls"].add(1)
        return self._L.tbrpc_arena_wait_reusable(self._h, off,
                                                 timeout_ms) == 0

    def close(self) -> None:
        if self._h:
            self._L.tbrpc_arena_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class TensorView:
    """A zero-copy window onto a received tensor (the peer's arena pages or
    the connection's RX segment). ``release()`` is what sends the release
    frame back and lets the sender reuse the range — call it (or use as a
    context manager) as soon as the bytes are consumed (e.g. after
    jax.device_put returns)."""

    def __init__(self, L, view_handle, ptr, nbytes, copied: bool):
        self._L = L
        self._view = view_handle
        self._ptr = ptr
        self._copied = copied
        self.nbytes = nbytes

    def ndarray(self) -> np.ndarray:
        buf = (ctypes.c_uint8 * self.nbytes).from_address(self._ptr)
        return np.ctypeslib.as_array(buf)

    @property
    def zero_copy(self) -> bool:
        return not self._copied

    def release(self) -> None:
        if self._view:
            self._L.tbrpc_view_free(self._view)
            self._view = None
        elif self._copied and self._ptr:
            self._L.tbrpc_free(self._ptr)
        self._ptr = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()

    def __del__(self):
        try:
            self.release()
        except Exception:  # noqa: BLE001
            pass


class TensorChannel:
    """Client stub for tensor traffic: a ``tpu://`` channel plus a local
    arena the outbound tensors stage through."""

    def __init__(self, addr: str, arena: Optional[TensorArena] = None,
                 timeout_ms: int = 20000, max_retry: int = 0):
        self._L = _bind_tensor_api(lib())
        if not addr.startswith("tpu://") and "://" not in addr:
            addr = "tpu://" + addr
        self._h = self._L.tbrpc_channel_create(addr.encode(), timeout_ms,
                                               max_retry)
        if not self._h:
            raise RuntimeError(f"tensor channel init to {addr} failed")
        native._LIVE_CHANNELS.add(self)  # atexit teardown hygiene
        self.arena = arena if arena is not None else TensorArena(256 << 20)

    def call_raw(self, service_method: str, request: bytes,
                 att_off: int = 0, att_len: int = 0
                 ) -> Tuple[bytes, TensorView]:
        """One RPC: request bytes + an arena range as the attachment.
        Returns (response payload, response-attachment view)."""
        if not self._h:
            # NULL through ctypes would be a native deref, not an error.
            raise RuntimeError("tensor channel is closed")
        L = self._L
        resp = ctypes.c_void_p()
        resp_len = ctypes.c_size_t()
        view = ctypes.c_void_p()
        ratt = ctypes.c_void_p()
        ratt_len = ctypes.c_size_t()
        copied = ctypes.c_int()
        errbuf = ctypes.create_string_buffer(256)
        rc = L.tbrpc_call_tensor(
            self._h, service_method.encode(), request, len(request),
            self.arena.handle if att_len else None, att_off, att_len,
            ctypes.byref(resp), ctypes.byref(resp_len), ctypes.byref(view),
            ctypes.byref(ratt), ctypes.byref(ratt_len), ctypes.byref(copied),
            errbuf, len(errbuf))
        if rc != 0:
            raise RpcError(rc, errbuf.value.decode(errors="replace"))
        try:
            payload = (ctypes.string_at(resp, resp_len.value)
                       if resp_len.value else b"")
        finally:
            L.tbrpc_free(resp)
        return payload, TensorView(L, view.value, ratt.value, ratt_len.value,
                                   bool(copied.value))

    def call(self, service_method: str, array=None, request: bytes = b""
             ) -> Tuple[bytes, Optional[np.ndarray]]:
        """Send a tensor (or nothing), receive a tensor (or nothing).

        The outbound array stages into the local arena (freed after the
        wire release returns); the inbound one is device_put-able — it is
        materialized as an ndarray COPY here only if the caller keeps it,
        via pull() below for the zero-copy discipline.
        """
        off = length = 0
        if array is not None:
            off, length, host = self.place_with_meta(array)
            request = _encode_meta(host) + request
        try:
            payload, view = self.call_raw(service_method, request, off,
                                          length)
        finally:
            if length:
                self.arena.free(off)  # deferred until releases drain
        with view:
            if view.nbytes == 0:
                try:  # an empty tensor still carries its metadata header
                    dtype, shape, rest = _decode_meta(payload)
                    return rest, np.empty(shape, dtype=dtype)
                except Exception:  # noqa: BLE001 — tensor-less response
                    return payload, None
            dtype, shape, rest = _decode_meta(payload)
            arr = np.frombuffer(view.ndarray(), dtype=dtype).reshape(shape)
            return rest, np.array(arr)  # detach before releasing the view

    def place_with_meta(self, array) -> Tuple[int, int, np.ndarray]:
        return self.arena.place(array)

    def pull_device(self, service_method: str, request: bytes = b"",
                    device=None):
        """Fetch a tensor and jax.device_put it STRAIGHT from the received
        view (H2D DMA from the shared pages; no intermediate host copy),
        then release the view. Returns (rest_of_payload, jax.Array).

        Observability: records into the tensor_pull LatencyRecorder and
        tensor_pull_bytes counter, and annotates the active rpcz span with
        the rpc / device_put stage split."""
        import jax

        t0 = time.monotonic()
        with _stage("rpc"):
            payload, view = self.call_raw(service_method, request)
        with view:
            dtype, shape, rest = _decode_meta(payload)
            arr = np.frombuffer(view.ndarray(), dtype=dtype).reshape(shape)
            nbytes = view.nbytes
            with _stage("device_put"):
                dev = jax.device_put(arr, device)
                dev.block_until_ready()  # H2D completes before the release
        m = _metrics()
        m["pull"].record_s(time.monotonic() - t0)
        m["pull_bytes"].add(nbytes)
        return rest, dev

    def push_device(self, service_method: str, array,
                    request: bytes = b"") -> bytes:
        """Send a device array (D2H into the arena, by-reference on the
        wire); waits for the wire release so the arena cannot fill up under
        a streaming push loop. Returns the response payload.

        Observability: records into the tensor_push LatencyRecorder and
        tensor_push_bytes counter, and annotates the active rpcz span with
        the arena_stage (D2H + staging copy) / rpc stage split."""
        t0 = time.monotonic()
        with _stage("arena_stage"):
            off, length, host = self.place_with_meta(array)
        try:
            with _stage("rpc"):
                payload, view = self.call_raw(
                    service_method, _encode_meta(host) + request, off, length)
            view.release()
            m = _metrics()
            m["push"].record_s(time.monotonic() - t0)
            m["push_bytes"].add(length)
            return payload
        finally:
            if length:
                self.arena.free(off)

    def close(self) -> None:
        if self._h:
            self._L.tbrpc_channel_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


# Handler: (method, request_bytes, attachment_view: np.ndarray|None)
#   -> (response_bytes, response_array_or_None)
TensorHandler = Callable[[str, bytes, Optional[np.ndarray]],
                         Tuple[bytes, Optional[object]]]


def add_tensor_service(server: native.Server, name: str,
                       handler: TensorHandler,
                       arena: Optional[TensorArena] = None) -> TensorArena:
    """Host a tensor service on a native Server: the handler reads request
    tensors IN PLACE (a numpy view of the sender's pages) and returns
    response tensors through the service's own arena (by-reference on the
    wire). Returns that arena."""
    L = _bind_tensor_api(lib())
    srv_arena = arena if arena is not None else TensorArena(256 << 20)

    def trampoline(ctx, method, req, req_len, att, att_len,
                   resp, resp_len, resp_arena, resp_off, resp_att_len,
                   resp_autofree, error_code, err_text, err_text_cap):
        t0 = time.monotonic()
        try:
            request = ctypes.string_at(req, req_len) if req_len else b""
            att_view = None
            if att_len:
                buf = (ctypes.c_uint8 * att_len).from_address(att)
                att_view = np.ctypeslib.as_array(buf)
                if request[:4] and len(request) >= 4:
                    # Typed sends prefix the payload with dtype/shape meta:
                    # give the handler a shaped view of the pages in place.
                    try:
                        dtype, shape, request = _decode_meta(request)
                        att_view = att_view.view(dtype).reshape(shape)
                    except Exception:  # noqa: BLE001 — raw-byte sender
                        pass
            r, out_arr = handler(method.decode(), request, att_view)
            if out_arr is not None:
                off, nbytes, host = srv_arena.place(out_arr)
                r = _encode_meta(host) + r
                if nbytes:
                    resp_arena[0] = srv_arena.handle
                    resp_off[0] = off
                    resp_att_len[0] = nbytes
                    # Autofree: the C side frees AFTER taking the response
                    # ref, so the range returns once the client releases.
                    resp_autofree[0] = 1
            if r:
                buf = L.tbrpc_alloc(len(r))
                ctypes.memmove(buf, r, len(r))
                resp[0] = buf
                resp_len[0] = len(r)
        except RpcError as e:
            error_code[0] = e.code if e.code != 0 else 2004
            fill_err_text(err_text, err_text_cap, e.text)
        except Exception as e:  # noqa: BLE001 — handler bug => EINTERNAL
            error_code[0] = 2004
            fill_err_text(err_text, err_text_cap, f"{type(e).__name__}: {e}")
        finally:
            # Handler + response staging: what the client's tensor_pull
            # would otherwise misattribute to the network.
            _metrics()["serve"].record_s(time.monotonic() - t0)

    cb = _TENSOR_CB(trampoline)
    server._cbs.append(cb)  # keep alive alongside byte-service callbacks
    if L.tbrpc_server_add_tensor_service(
            server._h, name.encode(), cb, None) != 0:
        raise RuntimeError(f"add_tensor_service({name}) failed")
    return srv_arena
