"""Quantized tensor wire format — the effective-bandwidth multiplier.

Both data planes sit at the transport's ~3 GB/s *byte* ceiling (PERF
rounds 6/8); the remaining lever is sending fewer bytes per tensor, not
moving bytes faster. This module is the host-side codec stage of that
lever, following EQuARX's design (PAPERS.md: block-wise quantized XLA
collectives with negligible quality loss):

  * **block-wise int8**: each run of ``block`` consecutive elements gets
    one fp32 scale (absmax/127); values ride as one signed byte each.
    4 logical bytes -> ~1.016 wire bytes at block=256 (a ~3.9x byte cut),
    with the per-block max-abs error bounded by scale/2.
  * **fp8-style e4m3, emulated**: same per-block scales mapping absmax to
    448 (the e4m3 max), each value stored as an e4m3 byte via ml_dtypes
    (bit-exact software emulation where hardware fp8 is unsupported).
    Wider dynamic range within a block than int8, ~2x the relative error.
  * **error feedback** for the gradient-push side: the quantization
    residual of push k is added to the gradient of push k+1 before
    quantizing (EF-SGD discipline), so repeated pushes cannot compound
    rounding bias — the *sum* of what the server receives tracks the sum
    of the true gradients to within one quantization step, independent of
    the number of pushes.

Negotiation rides the per-call compress/checksum pattern (COMPONENTS #64,
native/trpc/compress.cpp — gzip/snappy next to which the native registry
now also carries these tensor codec ids):

  * capability exchange: a ``ParameterServer`` advertises its codecs in
    the Meta document (cached per schema epoch by clients);
  * per-call request: a pull appends ``\\x00<codec>`` to the parameter
    name only after the server advertised it; pushes stamp the codec into
    the tensor metadata header;
  * self-describing response: the decode side is driven entirely by the
    header the bytes arrived with, never by what was requested — so a
    mixed fleet (or a server that declines a tensor: wrong dtype, too
    small) degrades to raw transparently, per call.

The raw path is byte-identical to the pre-codec wire: when no codec is
negotiated nothing here runs (pinned by tests/test_tensor_codec.py).
"""

from __future__ import annotations

import json
import struct
from typing import Dict, Optional, Tuple

import numpy as np

try:  # jax's dtype-extension package: bit-exact e4m3 emulation
    import ml_dtypes
    _F8 = np.dtype(ml_dtypes.float8_e4m3fn)
except Exception:  # noqa: BLE001 — fp8 gated off, int8 still works
    _F8 = None

# Wire codec ids — must match native/trpc/compress.h (the registry the
# /tensorz table and the negotiation advertisement read).
CODEC_RAW = 0
CODEC_INT8 = 1
CODEC_FP8E4M3 = 2

_NAME_TO_ID = {"int8": CODEC_INT8, "fp8e4m3": CODEC_FP8E4M3}
_ID_TO_NAME = {v: k for k, v in _NAME_TO_ID.items()}

DEFAULT_BLOCK = 256       # 4/256 = 1.56% scale overhead on the wire
MIN_QUANT_BYTES = 4096    # smaller tensors ride raw: savings < header noise
_E4M3_MAX = 448.0


def supported_codecs() -> Tuple[str, ...]:
    """Codecs this build can encode AND decode (fp8 needs ml_dtypes)."""
    return ("int8", "fp8e4m3") if _F8 is not None else ("int8",)


def codec_id(name: str) -> Optional[int]:
    return _NAME_TO_ID.get(name)


def codec_name(cid: int) -> Optional[str]:
    return _ID_TO_NAME.get(cid)


def choose(requested: Optional[str], advertised) -> Optional[str]:
    """Per-peer negotiation: the requested codec only if the peer
    advertised it AND this build supports it; else raw (None)."""
    if requested is None or advertised is None:
        return None
    if requested in advertised and requested in supported_codecs():
        return requested
    return None


def eligible(host: np.ndarray, min_bytes: int = MIN_QUANT_BYTES) -> bool:
    """Per-tensor eligibility: fp32 payloads above the size floor.
    Everything else rides raw — the per-call degrade path."""
    return host.dtype == np.float32 and host.nbytes >= min_bytes


class Encoded:
    """One quantized tensor ready for the wire.

    ``wire`` is a single contiguous uint8 array laid out as
    ``[nblocks x fp32 scales][n x 1-byte codes]`` — staged into the
    arena as-is; ``header`` is the metadata prefix the response/request
    payload carries (superset of the raw header: adds codec/block)."""

    __slots__ = ("wire", "header", "codec", "block", "logical_bytes",
                 "_scales", "_q", "_shape", "_dtype")

    def __init__(self, wire, header, codec, block, logical_bytes,
                 scales, q, shape, dtype):
        self.wire = wire
        self.header = header
        self.codec = codec
        self.block = block
        self.logical_bytes = logical_bytes
        self._scales = scales
        self._q = q
        self._shape = shape
        self._dtype = dtype

    @property
    def wire_bytes(self) -> int:
        return int(self.wire.nbytes)

    def dequantized(self) -> np.ndarray:
        """What the receiver will reconstruct (exact same math) — the
        error-feedback residual source."""
        flat = _dequant_flat(self.codec, self._q, self._scales, self.block)
        return flat.reshape(self._shape)


def pack_header(meta: dict) -> bytes:
    """Serialize a tensor metadata dict as the wire header prefix. This
    is the ONE implementation of the '<I length + JSON' framing — raw
    headers (tensor._encode_meta) delegate here with dtype/shape only,
    quantized ones add the codec/block fields."""
    doc = json.dumps(meta)
    return struct.pack("<I", len(doc)) + doc.encode()


_pack_header = pack_header  # internal alias


def _block_absmax(flat: np.ndarray, block: int) -> np.ndarray:
    n = flat.size
    nfull, tail = divmod(n, block)
    nblocks = nfull + (1 if tail else 0)
    absmax = np.empty(nblocks, np.float32)
    if nfull:
        np.abs(flat[:nfull * block].reshape(nfull, block)).max(
            axis=1, out=absmax[:nfull])
    if tail:
        absmax[nfull] = np.abs(flat[nfull * block:]).max()
    return absmax


def _scaled_codes(flat, absmax, block, target):
    """flat * (target/absmax) per block, tail-aware, one output pass."""
    n = flat.size
    nfull = n // block
    inv = np.zeros_like(absmax)  # all-zero blocks stay 0 -> exact codes
    np.divide(np.float32(target), absmax, out=inv, where=absmax > 0)
    y = np.empty(n, np.float32)
    if nfull:
        np.multiply(flat[:nfull * block].reshape(nfull, block),
                    inv[:nfull, None], out=y[:nfull * block].reshape(
                        nfull, block))
    if n % block:
        np.multiply(flat[nfull * block:], inv[nfull], out=y[nfull * block:])
    return y


def encode(host: np.ndarray, codec: str, block: int = DEFAULT_BLOCK,
           min_bytes: int = MIN_QUANT_BYTES) -> Optional[Encoded]:
    """Quantize ``host`` for the wire; None = this tensor rides raw
    (ineligible dtype/size or unknown codec) — the per-call degrade."""
    cid = codec_id(codec)
    if cid is None or codec not in supported_codecs():
        return None
    if not eligible(host, min_bytes):
        return None
    flat = np.ascontiguousarray(host).reshape(-1)
    absmax = _block_absmax(flat, block)
    if codec == "int8":
        y = _scaled_codes(flat, absmax, block, 127.0)
        np.rint(y, out=y)
        q = np.clip(y, -127.0, 127.0).astype(np.int8)
        scales = (absmax / np.float32(127.0)).astype(np.float32)
    else:  # fp8e4m3
        y = _scaled_codes(flat, absmax, block, _E4M3_MAX)
        q = y.astype(_F8)
        scales = (absmax / np.float32(_E4M3_MAX)).astype(np.float32)
    wire = np.empty(scales.nbytes + q.nbytes, np.uint8)
    wire[:scales.nbytes] = scales.view(np.uint8)
    wire[scales.nbytes:] = q.view(np.uint8)
    header = _pack_header({"dtype": host.dtype.str,
                           "shape": list(host.shape),
                           "codec": codec, "block": block})
    return Encoded(wire, header, codec, block, int(host.nbytes),
                   scales, q, host.shape, host.dtype)


def _dequant_flat(codec: str, q, scales, block: int) -> np.ndarray:
    """codes + per-block scales -> fresh fp32 array (always detached:
    the output never aliases arena/view pages)."""
    n = q.size
    nfull = n // block
    out = q.astype(np.float32)  # int8 or e4m3 -> fp32, one pass
    if nfull:
        view = out[:nfull * block].reshape(nfull, block)
        view *= scales[:nfull, None]
    if n % block:
        out[nfull * block:] *= scales[nfull]
    return out


def split_wire(meta: dict, payload: np.ndarray):
    """Slice a received ``[scales][codes]`` byte view into its typed
    parts (zero-copy views of the input)."""
    n = int(np.prod(meta["shape"], dtype=np.int64)) if meta["shape"] else 1
    block = int(meta["block"])
    nblocks = max(1, -(-n // block))
    if payload.size != nblocks * 4 + n:
        # Exact, not >=: numpy slicing would silently clamp a truncated
        # codes section and the failure would only surface deep in the
        # consumer (reshape in dequantize) as a generic internal error —
        # the server trampoline must be able to answer E_UNDECODABLE so
        # the client's codec self-heal engages.
        raise ValueError(
            f"quantized payload is {payload.size} bytes, header claims "
            f"{nblocks * 4 + n} ({nblocks} scales + {n} codes)")
    scales = payload[:nblocks * 4].view(np.float32)
    codes = payload[nblocks * 4:nblocks * 4 + n]
    if meta["codec"] == "int8":
        q = codes.view(np.int8)
    elif meta["codec"] == "fp8e4m3":
        if _F8 is None:
            raise ValueError("fp8e4m3 payload but ml_dtypes is unavailable")
        q = codes.view(_F8)
    else:
        raise ValueError(f"unknown tensor codec: {meta['codec']!r}")
    return q, scales


def decode(meta: dict, payload: np.ndarray) -> np.ndarray:
    """Received ``[scales][codes]`` bytes -> fp32 ndarray shaped per the
    header. The output is a fresh buffer (never aliases the view)."""
    q, scales = split_wire(meta, payload)
    flat = _dequant_flat(meta["codec"], q, scales, int(meta["block"]))
    out = flat.reshape(tuple(meta["shape"]))
    want = np.dtype(meta["dtype"])
    return out if want == np.float32 else out.astype(want)


class QuantizedView:
    """A quantized tensor received in place: ``q``/``scales`` are
    zero-copy views of the sender's pages (valid only while the request
    attachment is — i.e. inside the handler); ``dequantize()`` writes a
    fresh detached fp32 buffer, so consuming it IS the detach."""

    __slots__ = ("meta", "q", "scales", "shape", "dtype", "codec", "block",
                 "n", "nbytes", "wire_nbytes")

    def __init__(self, meta: dict, payload_u8: np.ndarray):
        self.meta = meta
        self.q, self.scales = split_wire(meta, payload_u8)
        self.shape = tuple(meta["shape"])
        self.dtype = np.dtype(meta["dtype"])
        self.codec = meta["codec"]
        self.block = int(meta["block"])
        self.n = int(np.prod(self.shape, dtype=np.int64))
        self.nbytes = self.n * self.dtype.itemsize  # logical bytes
        self.wire_nbytes = int(self.q.nbytes + self.scales.nbytes)

    def dequantize(self) -> np.ndarray:
        flat = _dequant_flat(self.codec, self.q, self.scales, self.block)
        out = flat.reshape(self.shape)
        return out if self.dtype == np.float32 else out.astype(self.dtype)


def error_bound(meta: dict, scales: np.ndarray) -> np.ndarray:
    """Per-block worst-case absolute reconstruction error: scale/2 for
    int8 (uniform step), scale * E4M3_MAX / 16 for e4m3 (3 mantissa bits
    -> half-ulp relative error of 2**-4 at the block max)."""
    if meta["codec"] == "int8":
        return scales * 0.5
    return scales * np.float32(_E4M3_MAX / 16.0)


class ErrorFeedback:
    """Per-name error-feedback accumulators for the gradient-push side.

    ``compensate(name, g)`` returns g + residual; after encoding x the
    caller reports the transmitted reconstruction via ``settle(name, x,
    dq)`` which stores the new residual x - dq. A raw-path push (codec
    declined) clears the name — nothing was lost, so nothing carries."""

    def __init__(self):
        self._residual: Dict[str, np.ndarray] = {}

    def compensate(self, name: str, g: np.ndarray) -> np.ndarray:
        e = self._residual.get(name)
        if e is None or e.shape != g.shape:
            return np.ascontiguousarray(g, dtype=np.float32)
        return (g + e).astype(np.float32, copy=False)

    def settle(self, name: str, x: np.ndarray, dq: np.ndarray) -> None:
        self._residual[name] = x - dq

    def set_residual(self, name: str, res: np.ndarray) -> None:
        """``settle`` for callers whose encoder already produced the
        residual (the collectives' fused kernel computes x - dq in the
        same XLA program as the quantization — re-deriving it here
        would cost the two passes the fusion saved)."""
        self._residual[name] = res

    def clear(self, name: str) -> None:
        self._residual.pop(name, None)

    def prune(self, keep) -> int:
        """Drop every residual whose name fails ``keep(name)``; returns
        the count dropped. Residuals are full-gradient-sized fp32 arrays
        held for the accumulator's lifetime — a caller whose routing
        changed (fleet reshard moved a name to another shard) must prune
        or N reshards leave every shard client holding residuals
        approaching the full parameter set."""
        dead = [n for n in list(self._residual) if not keep(n)]
        for n in dead:
            # pop, not del: a concurrent clear() (raw-path push on another
            # thread) may have already dropped the name since the snapshot.
            self._residual.pop(n, None)
        return len(dead)

    def residual(self, name: str) -> Optional[np.ndarray]:
        return self._residual.get(name)


# ---- wire accounting (native tensor_codec_* counters + /tensorz table) ----
# Strictly optional: noting rides the native library ONLY when some other
# part of the process already loaded it (every RPC peer has), so importing
# or unit-testing the codec never builds/loads the native stack.

_note_bound = False


def note(tensor: str, codec: str, logical_bytes: int, wire_bytes: int
         ) -> None:
    global _note_bound
    try:
        from brpc_tpu.runtime import native
        L = native._lib
        if L is None:
            return
        if not _note_bound:
            import ctypes
            L.tbrpc_tensor_codec_note.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64,
                ctypes.c_uint64]
            L.tbrpc_tensor_codec_note.restype = None
            _note_bound = True
        L.tbrpc_tensor_codec_note(tensor.encode(),
                                  codec_id(codec) or CODEC_RAW,
                                  logical_bytes, wire_bytes)
    except Exception:  # noqa: BLE001 — accounting must never break traffic
        pass
