"""ctypes bindings over the native C API (native/capi/capi.h).

The host RPC fabric (fiber scheduler, wait-free sockets, tstd protocol) is
C++; this module is the Python doorway: Server/Channel objects, Python
service handlers, and the bench harness entry points whose hot loops stay
in C. Handlers run on a small DEDICATED PTHREAD POOL on the native side
(capi PyCallbackPool, python_callback_threads flag), never on a fiber:
ctypes pairs PyGILState_Ensure/Release on one OS thread, and a fiber that
parks mid-handler (e.g. a nested RPC) could resume on a different worker.
The service fiber parks until the handler returns, and the handler's
thread carries the server's rpcz trace context, so downstream calls made
inside a handler link into the caller's trace.

Reference parity note: the reference's python/ tree is an empty "TBD" stub —
bindings here are first-class because the TPU data plane (JAX) is Python.
"""

from __future__ import annotations

import atexit
import contextlib
import ctypes
import errno
import os
import re
import subprocess
import weakref
from typing import Callable, Optional, Tuple

# Request priority lanes (native/trpc/qos.h): HIGH is the control plane
# (heartbeats, version polls, Epoch/Meta, migrator handshakes) — admitted
# up to the server's full concurrency gate; BULK is tensor pull/push —
# admitted only while the gate keeps headroom free; NORMAL is the unmarked
# default (wire stays byte-identical to the pre-QoS format).
PRIORITY_HIGH = 0
PRIORITY_NORMAL = 1
PRIORITY_BULK = 2

# Transport/framework error codes — the Python mirror of native/trpc/
# errno.h, name-for-name and value-for-value (tpulint's error-code rule
# pins the parity against tools/tpulint/error_codes.lock, so the two
# registries cannot drift apart silently). Clients and handlers key on
# THESE names; a raw integer comparison where one of these exists is a
# lint finding — the bare-literal collision class that once let a
# structural code land on top of TRPC_ECONNECT.
TRPC_ENOSERVICE = 1001      # no such service
TRPC_ENOMETHOD = 1002       # no such method
TRPC_EREQUEST = 1003        # malformed request
TRPC_ERESPONSE = 1005       # malformed response
TRPC_ERPCTIMEDOUT = 1008    # RPC deadline exceeded
TRPC_EBACKUPREQUEST = 1009  # internal: backup-request timer fired
TRPC_ELIMIT = 1011          # concurrency limit rejected the request
TRPC_ECANCELED = 1012       # RPC canceled by caller
TRPC_ENODATA = 1013         # no server available from LB/naming
TRPC_EEOF = 2001            # peer closed the connection
TRPC_EFAILEDSOCKET = 2002   # the socket was SetFailed while in use
TRPC_EINTERNAL = 2004       # server internal error
TRPC_EOVERCROWDED = 2006    # write queue over the in-flight cap
TRPC_ECONNECT = 2007        # connect failed

# The connection-killed subset: a stamped frame a pre-negotiation parser
# rejects surfaces client-side as one of these (the QoS self-heal keys
# on this tuple — see ParameterClient._qos_failed).
TRANSPORT_DEAD = (TRPC_EEOF, TRPC_EFAILEDSOCKET, TRPC_ECONNECT)

# Structural app-error codes, continuing the 2040+ range (param_server.py
# holds E_NO_SUCH 2040..E_EXISTS 2043, tensor.py E_UNDECODABLE 2044,
# collectives E_COLL_EPOCH 2045/E_COLL_ABORT 2046). These two are the
# serving fleet's routing signals; they live HERE so RpcError can
# classify them without importing the serving plane:
#   E_DRAINING      — the server refuses new sessions while it migrates
#                     its live ones out: retriable elsewhere/later, text
#                     carries the standard retry_after_ms pacer hint.
#   E_SESSION_MOVED — the session now lives on another server: the text
#                     carries "moved:<addr>" and the client FOLLOWS it
#                     (Gen/Resume), exactly the E_MOVED "moved:" shape
#                     the parameter fleet uses — keyed on the CODE, never
#                     on message strings.
E_DRAINING = 2047
E_SESSION_MOVED = 2048

_RETRY_AFTER_RE = re.compile(r"retry_after_ms=(\d+)")
_MOVED_RE = re.compile(r"moved:([^\s;,]+)")


def parse_moved(text: str) -> Optional[str]:
    """The ONE parser for the "moved:<addr>" forwarding grammar (error
    texts, E-frames, shed reasons) — every consumer (RpcError.moved_to,
    SessionShed.moved, the serving fleet's forwarding table) shares it
    so the grammar cannot drift between implementations."""
    if not text:
        return None
    m = _MOVED_RE.search(text)
    return m.group(1) if m else None

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_LIB_PATH = os.path.join(_REPO, "native", "build", "libbrpc_tpu.so")

_HANDLER_CB = ctypes.CFUNCTYPE(
    None,
    ctypes.c_void_p,                    # ctx
    ctypes.c_char_p,                    # method
    ctypes.c_void_p, ctypes.c_size_t,   # req
    ctypes.c_void_p, ctypes.c_size_t,   # attach
    ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),  # resp
    ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),  # resp_attach
    ctypes.POINTER(ctypes.c_int),       # error_code
    ctypes.c_void_p, ctypes.c_size_t,   # err_text buffer (C-owned)
)


def fill_err_text(err_text: int, err_text_cap: int, message: str) -> None:
    """Copy a handler failure message into the C-owned err_text buffer
    (NUL-terminated, truncated to cap-1) — it rides the wire back to the
    client's RpcError.text."""
    if not err_text or err_text_cap <= 1 or not message:
        return
    data = message.encode("utf-8", errors="replace")[:err_text_cap - 1]
    ctypes.memmove(err_text, data, len(data))
    ctypes.memset(err_text + len(data), 0, 1)

# PassiveStatus gauge callback: ctx -> current int64 value. Evaluated at
# scrape time under the native registry lock — keep the Python body trivial
# (no dump_vars/metric creation re-entry).
_GAUGE_CB = ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_void_p)

# /sessionz provider: fill the JSON document into (buf, cap) with the dump
# copy-out convention; runs on a callback-pool pthread at page-scrape time.
_SESSIONZ_CB = ctypes.CFUNCTYPE(
    ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t)

# HTTP streaming fallback handler: (ctx, path, query, progressive_id,
# body*, body_len*, use_progressive*, status*) — setting use_progressive=1
# turns the response into an unbounded chunked body fed afterwards via
# tbrpc_progressive_write(progressive_id, ...).
_HTTP_STREAM_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
    ctypes.c_uint64, ctypes.POINTER(ctypes.c_void_p),
    ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_int),
    ctypes.POINTER(ctypes.c_int))

_lib = None

# Native handles torn down during interpreter FINALIZATION (module-dict
# clearing order) abort in glibc — a client channel to a live in-process
# server destroyed that late double-frees. Destroying explicitly is always
# safe, so every wrapper registers here and one atexit hook (which runs
# BEFORE module teardown) closes channels first, then servers.
_LIVE_CHANNELS: "weakref.WeakSet" = weakref.WeakSet()
_LIVE_SERVERS: "weakref.WeakSet" = weakref.WeakSet()


def _teardown_native_handles() -> None:
    for ch in list(_LIVE_CHANNELS):
        try:
            ch.close()
        except Exception:  # noqa: BLE001 — best-effort exit hygiene
            pass
    for srv in list(_LIVE_SERVERS):
        try:
            srv.close()
        except Exception:  # noqa: BLE001
            pass


def _build_native() -> None:
    # Build-on-demand runs at the first lib() call, before any server,
    # channel, or fiber exists — there is no handler path to stall yet.
    build = os.path.join(_REPO, "native", "build")
    subprocess.run(  # tpulint: allow(py-blocking)
        ["cmake", "-S", "native", "-B", build, "-G", "Ninja",
         "-DCMAKE_BUILD_TYPE=RelWithDebInfo"],
        cwd=_REPO, check=True, capture_output=True)
    subprocess.run(  # tpulint: allow(py-blocking)
        ["cmake", "--build", build], cwd=_REPO, check=True,
        capture_output=True)


def lib() -> ctypes.CDLL:
    """Loads (building on demand) the native library."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        _build_native()
    L = ctypes.CDLL(_LIB_PATH)
    if not hasattr(L, "tbrpc_registry_install"):
        # Stale build from before the current bindings: the handler ABI
        # carries extra out-params now, so using it would marshal garbage
        # (not just miss symbols). Rebuild — and verify the reload took:
        # if the stale mapping was already dlopen'd, glibc hands the same
        # handle back and only a fresh process can pick up the new build.
        _build_native()
        L = ctypes.CDLL(_LIB_PATH)
        if not hasattr(L, "tbrpc_registry_install"):
            raise RuntimeError(
                "libbrpc_tpu.so was built before the current bindings and "
                "the stale mapping is already loaded in this process; the "
                "rebuild is on disk — restart Python to pick it up")
    L.tbrpc_server_create.restype = ctypes.c_void_p
    L.tbrpc_server_start.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    L.tbrpc_server_start_tls.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p]
    L.tbrpc_server_stop.argtypes = [ctypes.c_void_p]
    L.tbrpc_server_destroy.argtypes = [ctypes.c_void_p]
    L.tbrpc_server_add_echo_service.argtypes = [ctypes.c_void_p]
    L.tbrpc_server_set_inline.restype = ctypes.c_int
    L.tbrpc_server_set_inline.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    L.tbrpc_server_add_callback_service.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, _HANDLER_CB, ctypes.c_void_p]
    L.tbrpc_channel_create.restype = ctypes.c_void_p
    L.tbrpc_channel_create.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int]
    L.tbrpc_channel_create_ex.restype = ctypes.c_void_p
    L.tbrpc_channel_create_ex.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.c_int]
    L.tbrpc_channel_destroy.argtypes = [ctypes.c_void_p]
    L.tbrpc_call.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_void_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_alloc.restype = ctypes.c_void_p
    L.tbrpc_alloc.argtypes = [ctypes.c_size_t]
    L.tbrpc_free.argtypes = [ctypes.c_void_p]
    L.tbrpc_bench_echo_throughput.restype = ctypes.c_double
    L.tbrpc_bench_echo_throughput.argtypes = [
        ctypes.c_size_t, ctypes.c_int, ctypes.c_int]
    L.tbrpc_bench_echo_qps.restype = ctypes.c_double
    L.tbrpc_bench_echo_qps.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_double)]
    L.tbrpc_bench_echo_ex.restype = ctypes.c_double
    L.tbrpc_bench_echo_ex.argtypes = [
        ctypes.c_size_t, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double)]
    # ---- observability: metrics + dumps + tracing (capi.h) ----
    L.tbrpc_var_adder_create.restype = ctypes.c_void_p
    L.tbrpc_var_adder_create.argtypes = [ctypes.c_char_p]
    L.tbrpc_var_adder_add.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    L.tbrpc_var_adder_value.restype = ctypes.c_int64
    L.tbrpc_var_adder_value.argtypes = [ctypes.c_void_p]
    L.tbrpc_var_latency_create.restype = ctypes.c_void_p
    L.tbrpc_var_latency_create.argtypes = [ctypes.c_char_p]
    L.tbrpc_var_latency_record.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    L.tbrpc_var_latency_value.restype = ctypes.c_int64
    L.tbrpc_var_latency_value.argtypes = [ctypes.c_void_p, ctypes.c_int]
    L.tbrpc_var_gauge_create.restype = ctypes.c_void_p
    L.tbrpc_var_gauge_create.argtypes = [
        ctypes.c_char_p, _GAUGE_CB, ctypes.c_void_p]
    L.tbrpc_vars_dump.restype = ctypes.c_int64
    L.tbrpc_vars_dump.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_vars_dump_prometheus.restype = ctypes.c_int64
    L.tbrpc_vars_dump_prometheus.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_rpcz_dump_json.restype = ctypes.c_int64
    L.tbrpc_rpcz_dump_json.argtypes = [
        ctypes.c_uint64, ctypes.c_char_p, ctypes.c_size_t]
    # Hang forensics: callable from ANY plain pthread even when every
    # fiber worker is parked (how the socket-id-0 credit-leak wedge was
    # root-caused — see PERF.md round 6).
    L.tbrpc_debug_dump_fibers.restype = ctypes.c_int64
    L.tbrpc_debug_dump_fibers.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_debug_dump_ici.restype = ctypes.c_int64
    L.tbrpc_debug_dump_ici.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    # Flight recorder + stall watchdog (the self-monitoring layer): all of
    # these stay callable from any plain Python thread while every fiber
    # worker is parked — brpc_tpu.observability.health rides them.
    L.tbrpc_flight_snapshot.restype = ctypes.c_int64
    L.tbrpc_flight_snapshot.argtypes = [
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_flight_total_events.restype = ctypes.c_int64
    L.tbrpc_watchdog_start.restype = ctypes.c_int
    L.tbrpc_watchdog_start.argtypes = [ctypes.c_char_p]
    L.tbrpc_watchdog_stop.restype = ctypes.c_int
    L.tbrpc_health_state.restype = ctypes.c_int
    L.tbrpc_health_dump_json.restype = ctypes.c_int64
    L.tbrpc_health_dump_json.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_health_last_dump_path.restype = ctypes.c_int64
    L.tbrpc_health_last_dump_path.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_debug_hold_workers.restype = ctypes.c_int
    L.tbrpc_debug_hold_workers.argtypes = [ctypes.c_int, ctypes.c_int64]
    L.tbrpc_debug_induce_contention.restype = ctypes.c_int64
    L.tbrpc_debug_induce_contention.argtypes = [ctypes.c_int, ctypes.c_int64]
    L.tbrpc_rpcz_enabled.restype = ctypes.c_int
    L.tbrpc_rpcz_set_enabled.argtypes = [ctypes.c_int]
    # Head sampling for Python-created ROOT spans (trace_span): combines
    # rpcz_enabled with the reloadable rpcz_sample_1_in_n flag.
    L.tbrpc_rpcz_sample_root.restype = ctypes.c_int
    L.tbrpc_rpcz_sample_root.argtypes = []
    L.tbrpc_rpcz_sample_1_in_n.restype = ctypes.c_int
    L.tbrpc_rpcz_sample_1_in_n.argtypes = []
    L.tbrpc_trace_new_id.restype = ctypes.c_uint64
    L.tbrpc_trace_current.argtypes = [
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
    L.tbrpc_trace_set.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
    L.tbrpc_span_annotate.argtypes = [ctypes.c_char_p]
    L.tbrpc_span_emit.argtypes = [
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int, ctypes.c_char_p]
    L.tbrpc_now_us.restype = ctypes.c_int64
    L.tbrpc_flag_set.restype = ctypes.c_int
    L.tbrpc_flag_set.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    # Fleet: the process-global service registry (brpc_tpu/fleet rides it
    # over plain HTTP once installed; clear is test isolation).
    L.tbrpc_registry_install.restype = ctypes.c_int
    L.tbrpc_registry_install.argtypes = []
    L.tbrpc_registry_clear.restype = ctypes.c_int
    L.tbrpc_registry_clear.argtypes = []
    # Overload protection: ambient QoS context (priority lanes + tenant),
    # deadline propagation, per-tenant quotas, and the latency-injection
    # test hook (capi.h "overload protection" section).
    L.tbrpc_qos_set.restype = ctypes.c_int
    L.tbrpc_qos_set.argtypes = [ctypes.c_int, ctypes.c_char_p]
    L.tbrpc_qos_clear.restype = None
    L.tbrpc_qos_clear.argtypes = []
    L.tbrpc_qos_get.restype = ctypes.c_int64
    L.tbrpc_qos_get.argtypes = [
        ctypes.POINTER(ctypes.c_int), ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_deadline_remaining_ms.restype = ctypes.c_int64
    L.tbrpc_deadline_remaining_ms.argtypes = []
    L.tbrpc_server_set_max_concurrency.restype = ctypes.c_int
    L.tbrpc_server_set_max_concurrency.argtypes = [
        ctypes.c_void_p, ctypes.c_int32]
    L.tbrpc_server_set_tenant_quota.restype = ctypes.c_int
    L.tbrpc_server_set_tenant_quota.argtypes = [
        ctypes.c_void_p, ctypes.c_int32]
    L.tbrpc_server_tenantz_json.restype = ctypes.c_int64
    L.tbrpc_server_tenantz_json.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_debug_inject_latency.restype = ctypes.c_int
    L.tbrpc_debug_inject_latency.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    # Streaming RPC: the serving plane's transport (token streams over the
    # credit-windowed native Stream, tcp AND tpu://). Reads/writes run on
    # plain Python pthreads with the GIL released; a slow reader's
    # backpressure is confined to its own stream (manual consumption).
    L.tbrpc_stream_accept.restype = ctypes.c_int64
    L.tbrpc_stream_accept.argtypes = [ctypes.c_int64]
    L.tbrpc_stream_create.restype = ctypes.c_int64
    L.tbrpc_stream_create.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t), ctypes.c_char_p, ctypes.c_size_t]
    L.tbrpc_stream_write.restype = ctypes.c_int
    L.tbrpc_stream_write.argtypes = [
        ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int64]
    L.tbrpc_stream_read.restype = ctypes.c_int
    L.tbrpc_stream_read.argtypes = [
        ctypes.c_uint64, ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t)]
    L.tbrpc_stream_close.restype = ctypes.c_int
    L.tbrpc_stream_close.argtypes = [ctypes.c_uint64, ctypes.c_int]
    # Serving observability + HTTP streaming fallback.
    L.tbrpc_sessionz_set_provider.restype = ctypes.c_int
    L.tbrpc_sessionz_set_provider.argtypes = [_SESSIONZ_CB, ctypes.c_void_p]
    L.tbrpc_http_stream_register.restype = ctypes.c_int
    L.tbrpc_http_stream_register.argtypes = [
        ctypes.c_char_p, _HTTP_STREAM_CB, ctypes.c_void_p]
    L.tbrpc_progressive_write.restype = ctypes.c_int
    L.tbrpc_progressive_write.argtypes = [
        ctypes.c_uint64, ctypes.c_void_p, ctypes.c_size_t]
    L.tbrpc_progressive_close.restype = ctypes.c_int
    L.tbrpc_progressive_close.argtypes = [ctypes.c_uint64]
    _lib = L
    atexit.register(_teardown_native_handles)
    return L


@contextlib.contextmanager
def qos(priority: int = PRIORITY_NORMAL, tenant: str = ""):
    """Ambient QoS for calls issued inside the scope (THIS thread only —
    the native slot is per-thread, like the trace context): requests stamp
    `priority` (PRIORITY_HIGH/NORMAL/BULK) and `tenant` onto the wire, and
    the server's admission uses both (priority lanes + per-tenant quotas).
    With neither set, the wire stays byte-identical to the pre-QoS format.

    Nestable: exit restores the REAL surrounding ambient values (read back
    through the native slot), so a scope used inside a server handler —
    whose thread carries the request's own priority/tenant, installed
    natively — hands the handler's context back intact. The propagated
    DEADLINE lives in the same slot but is untouched by set/restore, so
    nested-call clamping survives any qos() nesting. Raises ValueError
    for tenants over the 256-byte wire cap."""
    L = lib()
    prev_prio = ctypes.c_int()
    prev_tenant = ctypes.create_string_buffer(512)  # cap is 256
    L.tbrpc_qos_get(ctypes.byref(prev_prio), prev_tenant, len(prev_tenant))
    if L.tbrpc_qos_set(priority,
                       tenant.encode() if tenant else b"") != 0:
        raise ValueError(f"tenant id too long ({len(tenant)} bytes > 256)")
    try:
        yield
    finally:
        L.tbrpc_qos_set(prev_prio.value, prev_tenant.value)


def deadline_remaining_ms() -> Optional[int]:
    """Remaining budget (ms) of the request this thread is handling —
    the deadline the client propagated, minus time already burned. None
    when no deadline is in scope (not inside a handler, or the client set
    no timeout). 0 means expired: shed the work, the caller is gone."""
    left = lib().tbrpc_deadline_remaining_ms()
    return None if left < 0 else int(left)


def inject_latency(service: str, ms: int) -> None:
    """TEST-ONLY (beside debug hold_workers): every admitted request to
    `service` holds its gate slot for `ms` before the handler runs —
    deterministic queueing for overload/shed tests. ms <= 0 clears;
    service='' clears all injections."""
    lib().tbrpc_debug_inject_latency(service.encode(), ms)


# Handler signature: (method: str, request: bytes, attachment: bytes)
#   -> (response: bytes, response_attachment: bytes) — raise RpcError to fail.
Handler = Callable[[str, bytes, bytes], Tuple[bytes, bytes]]


class RpcError(Exception):
    def __init__(self, code: int, text: str = ""):
        overloaded = code in (TRPC_ELIMIT, TRPC_EOVERCROWDED)
        super().__init__(
            f"rpc error {code}"
            + (" (server overloaded — back off)" if overloaded else "")
            + f": {text}")
        self.code = code
        self.text = text
        # Shed responses carry a computed drain-time hint in their text
        # (" (retry_after_ms=N)", from the server's EMA latency): clients
        # pace their retry on it instead of hot-looping into the shed
        # storm. None when the error carries no hint.
        m = _RETRY_AFTER_RE.search(text) if text else None
        self.retry_after_ms: Optional[int] = int(m.group(1)) if m else None

    @property
    def overloaded(self) -> bool:
        """True for the overload-shed codes (ELIMIT / EOVERCROWDED):
        retriable with backoff, and NEVER evidence that a parameter moved
        or a shard died (the fleet retry layer keeps them out of its
        reshard handling)."""
        return self.code in (TRPC_ELIMIT, TRPC_EOVERCROWDED)

    @property
    def draining(self) -> bool:
        """True when the server refused because it is draining
        (E_DRAINING): the request is fine, THIS server is leaving —
        retriable on another member, paced by retry_after_ms like an
        overload shed, but never counted as overload/capacity evidence."""
        return self.code == E_DRAINING

    @property
    def moved_to(self) -> Optional[str]:
        """The forwarding address of an E_SESSION_MOVED redirect (the
        "moved:<addr>" the text carries), or None — classification keys
        on the code; only a moved error is ever parsed for an address."""
        if self.code != E_SESSION_MOVED:
            return None
        return parse_moved(self.text)


class Server:
    """A native RPC server hosting Python (and native) services."""

    def __init__(self):
        self._L = lib()
        self._h = self._L.tbrpc_server_create()
        self._cbs = []  # keep CFUNCTYPE objects alive
        self.port: Optional[int] = None
        _LIVE_SERVERS.add(self)

    def add_echo_service(self) -> None:
        if self._L.tbrpc_server_add_echo_service(self._h) != 0:
            raise RuntimeError("add_echo_service failed")

    def set_inline(self, service: str, enabled: bool = True) -> None:
        """Run SMALL requests to `service` directly on the input fiber (the
        small-RPC inline fast path), skipping the dispatch hop.

        Only native services whose implementation declares itself
        non-blocking qualify; Python handler services are ALWAYS refused —
        they park the fiber on the GIL-safe callback pool, and a parked
        input fiber would head-of-line-block its whole connection."""
        if self._L.tbrpc_server_set_inline(
                self._h, service.encode(), 1 if enabled else 0) != 0:
            raise RuntimeError(
                f"set_inline({service!r}) refused: unknown service or not "
                "inline-safe (Python handlers always run on the callback "
                "pool)")

    def set_max_concurrency(self, max_inflight: int) -> None:
        """Concurrency gate applied at start() (0 = unlimited). Requests
        over the cap shed with ELIMIT + a retry_after_ms hint; the BULK
        lane additionally keeps rpc_bulk_headroom_pct of the gate free
        for control-plane traffic. Must be called BEFORE start()."""
        if self._L.tbrpc_server_set_max_concurrency(
                self._h, max_inflight) != 0:
            raise RuntimeError(
                "set_max_concurrency must be called before start()")

    def set_tenant_quota(self, max_inflight: int) -> None:
        """Per-tenant in-flight quota layered under the global gate
        (0 = off): each tenant (QoS meta field, falling back to the peer
        ip) sheds its own overflow before it can crowd out others.
        Runtime-safe."""
        if self._L.tbrpc_server_set_tenant_quota(self._h, max_inflight) != 0:
            raise RuntimeError("set_tenant_quota failed")

    def tenantz(self) -> dict:
        """The per-tenant admission table: {"quota": N, "tenants":
        [{name, admitted, shed, inflight, quota}, ...]} — the same
        document /tenantz?format=json serves."""
        import json as _json

        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            need = self._L.tbrpc_server_tenantz_json(self._h, buf, cap)
            if need < cap:
                return _json.loads(buf.value.decode())
            cap = int(need) + 1

    def add_service(self, name: str, handler: Handler) -> None:
        L = self._L

        def trampoline(ctx, method, req, req_len, att, att_len,
                       resp, resp_len, resp_att, resp_att_len, error_code,
                       err_text, err_text_cap):
            try:
                request = ctypes.string_at(req, req_len) if req_len else b""
                attachment = ctypes.string_at(att, att_len) if att_len else b""
                r, ra = handler(method.decode(), request, attachment)
                for data, pp, pl in ((r, resp, resp_len),
                                     (ra, resp_att, resp_att_len)):
                    if data:
                        buf = L.tbrpc_alloc(len(data))
                        ctypes.memmove(buf, data, len(data))
                        pp[0] = buf
                        pl[0] = len(data)
            except RpcError as e:
                error_code[0] = e.code if e.code != 0 else TRPC_EINTERNAL
                fill_err_text(err_text, err_text_cap, e.text)
            except Exception as e:  # noqa: BLE001 — handler bug => EINTERNAL
                error_code[0] = TRPC_EINTERNAL
                fill_err_text(err_text, err_text_cap,
                              f"{type(e).__name__}: {e}")

        cb = _HANDLER_CB(trampoline)
        self._cbs.append(cb)
        if L.tbrpc_server_add_callback_service(
                self._h, name.encode(), cb, None) != 0:
            raise RuntimeError(f"add_service({name}) failed")

    def start(self, addr: str = "127.0.0.1:0", *, ssl_cert: str = "",
              ssl_key: str = "") -> int:
        """ssl_cert+ssl_key make the port ALSO accept TLS (sniffed, so
        plaintext clients keep working; ALPN offers h2 for gRPC-over-TLS)."""
        if not self._h:
            raise RuntimeError("server is closed")
        if ssl_cert or ssl_key:
            port = self._L.tbrpc_server_start_tls(
                self._h, addr.encode(), ssl_cert.encode(), ssl_key.encode())
        else:
            port = self._L.tbrpc_server_start(self._h, addr.encode())
        if port < 0:
            raise RuntimeError(f"server start on {addr} failed")
        self.port = port
        return port

    def stop(self) -> None:
        if self._h:  # no-op after close (stop-in-finally patterns)
            self._L.tbrpc_server_stop(self._h)

    def close(self) -> None:
        """Stop and release the native server (idempotent)."""
        if self._h:
            self._L.tbrpc_server_stop(self._h)
            self._L.tbrpc_server_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class Channel:
    """Client stub to one server ("ip:port")."""

    def __init__(self, addr: str, timeout_ms: int = 1000, max_retry: int = 3,
                 protocol: str = "tstd"):
        """protocol: "tstd" (native framing) or "grpc" (gRPC over HTTP/2 —
        dials any standard gRPC server)."""
        self._L = lib()
        protos = {"tstd": 0, "grpc": 5}
        if protocol not in protos:
            raise ValueError(
                f"unknown protocol {protocol!r}; choose from {sorted(protos)}")
        proto = protos[protocol]
        self._h = self._L.tbrpc_channel_create_ex(
            addr.encode(), timeout_ms, max_retry, proto)
        if not self._h:
            raise RuntimeError(f"channel init to {addr} failed")
        _LIVE_CHANNELS.add(self)

    def call(self, service_method: str, request: bytes = b"",
             attachment: bytes = b"") -> Tuple[bytes, bytes]:
        if not self._h:
            # NULL through ctypes would be a native deref, not an error.
            raise RuntimeError("channel is closed")
        L = self._L
        resp = ctypes.c_void_p()
        resp_len = ctypes.c_size_t()
        resp_att = ctypes.c_void_p()
        resp_att_len = ctypes.c_size_t()
        errbuf = ctypes.create_string_buffer(256)
        rc = L.tbrpc_call(
            self._h, service_method.encode(),
            request, len(request), attachment, len(attachment),
            ctypes.byref(resp), ctypes.byref(resp_len),
            ctypes.byref(resp_att), ctypes.byref(resp_att_len),
            errbuf, len(errbuf))
        if rc != 0:
            raise RpcError(rc, errbuf.value.decode(errors="replace"))
        try:
            r = ctypes.string_at(resp, resp_len.value) if resp_len.value else b""
            ra = (ctypes.string_at(resp_att, resp_att_len.value)
                  if resp_att_len.value else b"")
        finally:
            L.tbrpc_free(resp)
            L.tbrpc_free(resp_att)
        return r, ra

    def close(self) -> None:
        """Release the native channel (idempotent)."""
        if self._h:
            self._L.tbrpc_channel_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


# ---------------------------------------------------------------------------
# Streaming RPC: the serving plane's transport.
# ---------------------------------------------------------------------------

class StreamClosed(Exception):
    """The peer closed the stream (EOF). ``error`` carries the close code
    (0 = clean close); an abnormal close (connection death, server shed)
    surfaces it so readers can distinguish 'generation finished' from
    'stream died'."""

    def __init__(self, error: int = 0):
        super().__init__("stream closed"
                         + (f" (error {error})" if error else ""))
        self.error = error


class Stream:
    """One half of a native credit-windowed message stream (trpc/stream.h
    over the capi): ordered messages, per-stream flow control on BOTH
    transports (tcp and tpu://). Reads/writes block only the calling
    Python thread (ctypes releases the GIL); a slow reader exhausts ITS
    OWN peer window — never another stream's.

    Obtained from :func:`open_stream` (client) or :func:`accept_stream`
    (inside a server handler). Always :meth:`close` (context manager
    supported): the native read buffer lives until then."""

    def __init__(self, stream_id: int):
        self._L = lib()
        self.id = int(stream_id)
        self._closed = False

    def write(self, data: bytes, timeout_ms: int = -1) -> bool:
        """Send one message. timeout_ms < 0 blocks until the peer's
        window opens (credit backpressure), 0 probes, > 0 bounds the
        wait. Returns False when the window stayed exhausted for the
        whole bound (the caller buffers or sheds THIS stream); raises
        StreamClosed once the stream is gone."""
        rc = self._L.tbrpc_stream_write(self.id, data, len(data),
                                        timeout_ms)
        if rc == 0:
            return True
        if rc == errno.EAGAIN:  # credit stayed exhausted for the bound
            return False
        raise StreamClosed(rc)

    def read(self, timeout_ms: int = -1) -> Optional[bytes]:
        """Next message in order, or None on timeout. Raises StreamClosed
        at EOF (after the queue drained); consumption feedback — the
        peer's write credit — advances with each message taken here."""
        L = self._L
        data = ctypes.c_void_p()
        length = ctypes.c_size_t()
        rc = L.tbrpc_stream_read(self.id, timeout_ms, ctypes.byref(data),
                                 ctypes.byref(length))
        if rc == 0:
            try:
                return (ctypes.string_at(data, length.value)
                        if length.value else b"")
            finally:
                L.tbrpc_free(data)
        if rc == -1:
            return None
        if rc in (1, -2):
            raise StreamClosed(0)
        raise StreamClosed(rc)

    def close(self, error: int = 0) -> None:
        """Close the local half and release the native read buffer.
        error > 0 rides the CLOSE control frame (bypassing the data
        credit window): the peer's reads drain, then raise StreamClosed
        with that code instead of a clean EOF — how a server shed stays
        visible to a reader whose window is full. Idempotent."""
        if not self._closed:
            self._closed = True
            self._L.tbrpc_stream_close(self.id, error)

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def open_stream(channel: Channel, service_method: str,
                request: bytes = b"", *,
                max_buf_size: int = 0) -> Tuple[Stream, bytes]:
    """Open `service_method` with a stream attached (the RPC carries the
    handshake; the handler must call :func:`accept_stream`). Returns the
    CONNECTED stream and the RPC response body. max_buf_size (<= 0 =
    default 2MB) is OUR receive window — the peer's write budget."""
    if not channel._h:
        raise RuntimeError("channel is closed")
    L = lib()
    resp = ctypes.c_void_p()
    resp_len = ctypes.c_size_t()
    errbuf = ctypes.create_string_buffer(256)
    sid = L.tbrpc_stream_create(
        channel._h, service_method.encode(), request, len(request),
        max_buf_size, ctypes.byref(resp), ctypes.byref(resp_len),
        errbuf, len(errbuf))
    if sid <= 0:
        raise RpcError(int(-sid) if sid < 0 else TRPC_EINTERNAL,
                       errbuf.value.decode(errors="replace"))
    try:
        body = (ctypes.string_at(resp, resp_len.value)
                if resp_len.value else b"")
    finally:
        L.tbrpc_free(resp)
    return Stream(sid), body


def accept_stream(max_buf_size: int = 0) -> Optional[Stream]:
    """Accept the client's stream from INSIDE a service handler (the
    callback-pool thread), before returning — the response carries the
    acceptance. None when the client didn't attach a stream (or called
    outside a handler). max_buf_size is the server's receive window."""
    sid = lib().tbrpc_stream_accept(max_buf_size)
    return Stream(sid) if sid > 0 else None


# CFUNCTYPE trampolines registered with process-lifetime native slots must
# never be collected while native may still call them (HTTP handlers).
_immortal_native_cbs: list = []

# The /sessionz provider slot holds exactly ONE trampoline: the native
# side swaps AND scrapes under one mutex, so once
# tbrpc_sessionz_set_provider returns, the previous trampoline can never
# be called again — releasing it here (instead of an immortal append)
# keeps a replaced provider's closure (a whole SessionManager graph) from
# being pinned for the process lifetime.
_sessionz_holder: dict = {"fn": None, "cb": None}


def set_sessionz_provider(fn: Optional[Callable[[], str]]) -> None:
    """(Re)point the /sessionz console page at `fn` (returns the JSON
    document string); None clears it. The callback runs on a pool pthread
    at page-scrape time — keep it snapshot-cheap."""
    L = lib()
    if fn is None:
        L.tbrpc_sessionz_set_provider(ctypes.cast(None, _SESSIONZ_CB),
                                      None)
        _sessionz_holder["fn"] = _sessionz_holder["cb"] = None
        return

    def _cb(_ctx, buf, cap) -> int:
        try:
            doc = fn().encode()
        except Exception:  # noqa: BLE001 — a failing provider reads empty
            doc = b"{}"
        if buf and cap > 0:
            n = min(len(doc), cap - 1)
            ctypes.memmove(buf, doc, n)
            ctypes.memset(buf + n, 0, 1)
        return len(doc)

    cb = _SESSIONZ_CB(_cb)
    L.tbrpc_sessionz_set_provider(cb, None)
    _sessionz_holder["fn"] = fn
    _sessionz_holder["cb"] = cb  # old trampoline unreferenced -> GC


def clear_sessionz_provider(fn: Callable[[], str]) -> None:
    """Clear the /sessionz provider IF `fn` is still the registered one
    (a shutdown must not clear a newer manager's registration)."""
    if _sessionz_holder["fn"] is fn:
        set_sessionz_provider(None)


# HTTP streaming fallback handler signature:
#   (path: str, query: str, progressive_id: int)
#     -> (status: int, body: bytes, progressive: bool)
# progressive=True keeps the response open; feed it with
# progressive_write(progressive_id, ...) then progressive_close(...).
HttpStreamHandler = Callable[[str, str, int], Tuple[int, bytes, bool]]


def register_http_stream_handler(path: str, fn: HttpStreamHandler) -> None:
    """Serve `path` on every server's builtin HTTP port with optional
    ProgressiveAttachment streaming — the plain-HTTP fallback for token
    streams (curl consumes them without speaking tstd)."""
    L = lib()

    def _cb(_ctx, cpath, cquery, pid, body, body_len, use_prog, status):
        try:
            st, payload, progressive = fn(
                cpath.decode() if cpath else "",
                cquery.decode() if cquery else "", int(pid))
        except Exception as e:  # noqa: BLE001 — handler bug => 500
            st, payload, progressive = 500, f"{type(e).__name__}: {e}\n"\
                .encode(), False
        status[0] = int(st)
        use_prog[0] = 1 if progressive else 0
        if payload:
            buf = L.tbrpc_alloc(len(payload))
            ctypes.memmove(buf, payload, len(payload))
            body[0] = buf
            body_len[0] = len(payload)

    cb = _HTTP_STREAM_CB(_cb)
    _immortal_native_cbs.append(cb)
    if L.tbrpc_http_stream_register(path.encode(), cb, None) != 0:
        raise RuntimeError(f"http path already registered: {path!r}")


def progressive_write(progressive_id: int, data: bytes) -> bool:
    """Feed a progressive HTTP response; False once the peer is gone."""
    return lib().tbrpc_progressive_write(
        progressive_id, data, len(data)) == 0


def progressive_close(progressive_id: int) -> None:
    """Terminal chunk; the connection closes after it drains."""
    lib().tbrpc_progressive_close(progressive_id)


def bench_echo_throughput(payload_size: int, seconds: int = 2,
                          concurrency: int = 4) -> float:
    """One-way payload bytes/sec through a loopback echo server."""
    return lib().tbrpc_bench_echo_throughput(payload_size, seconds,
                                             concurrency)


def bench_echo_qps(seconds: int = 2, concurrency: int = 8):
    """(calls/sec, p99_us) for small-payload loopback echo."""
    p99 = ctypes.c_double()
    qps = lib().tbrpc_bench_echo_qps(seconds, concurrency, ctypes.byref(p99))
    return qps, p99.value


def bench_echo_ex(payload_size: int, seconds: int = 2, concurrency: int = 4,
                  transport: str = "tcp", conn_type: str = "single"):
    """One bench point with full control.

    Returns (oneway_bytes_per_sec, calls_per_sec, p50_us, p99_us).
    transport: "tcp" | "tpu" (shm ICI transport over the loopback control
    channel). conn_type: "single" | "pooled" | "short".
    """
    qps = ctypes.c_double()
    p50 = ctypes.c_double()
    p99 = ctypes.c_double()
    bps = lib().tbrpc_bench_echo_ex(
        payload_size, seconds, concurrency,
        {"tcp": 0, "tpu": 1}[transport],
        {"single": 0, "pooled": 1, "short": 2}[conn_type],
        ctypes.byref(qps), ctypes.byref(p50), ctypes.byref(p99))
    return bps, qps.value, p50.value, p99.value
