"""Grouped-tensor manifest framing — the ONE wire shape every grouped
tensor RPC speaks.

PR 7's PullQ established the pattern: a JSON manifest describing N
tensors rides the RPC payload, the N encoded byte runs ride concatenated
in ONE attachment, and per-name failures ride the manifest as
``{"name", "code", "error"}`` entries instead of poisoning groupmates
(the per-name salvage discipline). PushQ (the write-side twin) and the
collectives' hop writes speak the same shape; this module is its single
implementation so the three paths cannot drift:

  * each payload entry carries the tensor's self-describing metadata
    (``dtype``/``shape``, plus ``codec``/``block`` when quantized — the
    same keys ``codec.pack_header`` frames for single-tensor sends) and
    ``nbytes``, its run length in the shared attachment;
  * error entries carry ``code``/``error`` and NO payload run;
  * runs are concatenated in entry order with no padding, so the
    receiver slices by a running offset exactly like PullQ's client.

Pure numpy/json on purpose: the collectives' tier-1 units frame and
split groups with no native library loaded.
"""

from __future__ import annotations

import json
from typing import Iterator, List, Optional, Tuple

import numpy as np


def pack_group(entries: List[dict], blobs: List[Optional[np.ndarray]],
               extra: Optional[dict] = None) -> Tuple[bytes, np.ndarray]:
    """Frame a group: ``entries[i]`` describes ``blobs[i]`` (``None`` for
    error entries). Returns ``(manifest_bytes, concat_u8)``; the caller
    sends the manifest as the request payload and the concatenation as
    the attachment. ``extra`` merges top-level manifest keys beside
    ``tensors`` (the collectives stamp op/epoch routing there)."""
    if len(entries) != len(blobs):
        raise ValueError(f"{len(entries)} entries vs {len(blobs)} blobs")
    out_entries, runs, total = [], [], 0
    for e, b in zip(entries, blobs):
        e = dict(e)
        if b is None:
            e.pop("nbytes", None)  # error entries own no payload run
        else:
            flat = np.ascontiguousarray(b).reshape(-1).view(np.uint8)
            e["nbytes"] = int(flat.nbytes)
            runs.append(flat)
            total += flat.nbytes
        out_entries.append(e)
    doc = {"tensors": out_entries}
    if extra:
        doc.update(extra)
    concat = np.empty(total, np.uint8)
    off = 0
    for r in runs:
        concat[off:off + r.nbytes] = r
        off += r.nbytes
    return json.dumps(doc).encode(), concat


def split_group(manifest: dict, payload) -> Iterator[Tuple[dict,
                                                           Optional[np.ndarray]]]:
    """Walk a received group: yields ``(entry, run_u8_view)`` per entry
    (``None`` run for error entries). ``payload`` is the attachment as a
    1-D uint8 array/view (or ``None``/``b""`` for an all-error group —
    the PullQ zero-attachment case). Runs are zero-copy views of the
    input; detach before the view's pages can be reused. A manifest
    whose claimed runs overrun the payload raises ``ValueError`` (the
    receiver maps it to E_UNDECODABLE)."""
    if payload is None:
        buf = np.empty(0, np.uint8)
    else:
        buf = np.asarray(payload).reshape(-1).view(np.uint8)
    off = 0
    for e in manifest["tensors"]:
        if "error" in e:
            yield e, None
            continue
        nb = int(e.get("nbytes", 0))
        if off + nb > buf.nbytes:
            raise ValueError(
                f"group manifest overruns payload: entry {e.get('name')!r}"
                f" claims {nb} bytes at offset {off} of {buf.nbytes}")
        yield e, buf[off:off + nb]
        off += nb


def parse_group(request: bytes) -> dict:
    """The manifest side of the frame (request payload -> dict)."""
    return json.loads(request.decode())
