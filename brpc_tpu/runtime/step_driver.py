"""Overlapped training step driver — hide the tensor wire behind compute.

The flagship training loop used to run compute -> gradient push ->
next-step pull strictly serially, so every wire byte was exposed step
time even though ``PipelineWindow`` (#83) already overlaps arena staging
with the wire one level down. This driver lifts that overlap to the
WHOLE step (PAPERS.md T3: fine-grained compute/communication overlap is
where the step time hides): the step decomposes into per-tensor nodes

    forward -> bwd:k (compute lane, top layer first)
    bwd:k   -> push:k -> opt:k -> pull:k (wire lane)

scheduled by the tier-1-pure :mod:`step_sched` core, so the gradient
push of layer k (encode included — the PR 7 ``encoder=`` hook runs at
arena-stage time on the wire lane) overlaps backward compute of the next
layer, and next-step pulls overlap the server-side optimizer applies of
the remaining pushes. Everything rides the EXISTING client machinery:
pushes go through one bounded :class:`PipelineWindow` per step (async
futures, submit-order replies, ``complete_one`` as the per-tensor
confirm point), pulls through ``client.pull`` (one-sided reads when the
client maps the server's window, quantized when negotiated, QoS-stamped,
paced). ``overlap=False`` runs the SAME nodes serially on one thread —
today's driver exactly, the A/B baseline.

Failure semantics: a mid-step push failure cancels only its dependents,
every other branch completes, and the step raises
:class:`~brpc_tpu.runtime.param_server.PartialPushError` with the
versions that DID land (``applied``) vs the names with no confirmed
apply (``unpushed``) — re-pushing an applied gradient double-steps the
server's momentum, so salvage must be per-name (the PR 7 discipline).

Instrumented end to end: one ``train_step`` rpcz root span per step with
a child span per node (wire-side spans carry the PipelineWindow's
``arena_stage``/``wire_wait`` and the driver's ``encode`` stage
annotations, so a trace VISIBLY shows push spans inside the next layer's
compute span), plus ``step_exposed_comm_ms`` / ``step_overlapped_comm_ms``
recorders on /vars (samples in milliseconds, as named).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

from brpc_tpu.runtime import native
from brpc_tpu.runtime.param_server import PartialPushError
from brpc_tpu.runtime.step_sched import (COMPUTE, WIRE, StepFailure,
                                         StepGraph, run_graph)
from brpc_tpu.runtime.tensor import PipelineWindow

_metrics_cache = None


def _trace_handoff_ctx(tid: int, sid: int, qos=None):
    """The wire-lane context factory both drivers hand to ``run_graph``:
    each lane's thread inherits the step's rpcz trace context (and,
    when ``qos`` — a zero-arg context-manager factory — is given, the
    BULK QoS stamp: the FleetClient worker-thread discipline). Restore,
    don't clear, on exit: in serial mode this wraps the CALLER's own
    thread, whose ambient context must survive the step."""

    @contextlib.contextmanager
    def wire_ctx():
        from brpc_tpu.observability import tracing

        had_t, had_s = tracing.current_trace()
        if tid:
            tracing.set_trace(tid, sid)
        try:
            with (qos() if qos is not None
                  else contextlib.nullcontext()):
                yield
        finally:
            if tid:
                if had_t or had_s:
                    tracing.set_trace(had_t, had_s)
                else:
                    tracing.clear_trace()

    return wire_ctx


def _metrics():
    global _metrics_cache
    if _metrics_cache is None:
        from brpc_tpu.observability import metrics as obs

        _metrics_cache = {
            # Full step wall time (us, the standard recorder unit).
            "step": obs.latency("step_driver_step"),
            # Samples are MILLISECONDS, as the names say: the step
            # breakdown reads naturally next to wall-clock step times
            # (a 1MB-scale step is tens of ms; us percentiles of comm
            # slices would render as noise-width integers).
            "exposed": obs.latency("step_exposed_comm_ms"),
            "overlapped": obs.latency("step_overlapped_comm_ms"),
            "steps": obs.counter("step_driver_steps"),
            "partial": obs.counter("step_driver_partial_failures"),
        }
    return _metrics_cache


class OverlappedStepDriver:
    """Drive an RPC training loop over a layered harness.

    ``client``: a :class:`ParameterClient` (pushes ride one
    ``PipelineWindow`` per step over its channel) or any fleet-shaped
    object with ``pull``/``push_grad``/``pull_all`` (pushes confirm
    synchronously per name — the FleetClient path, where windowing lives
    inside each shard stream).

    ``harness`` protocol (see ``models.tensor_service.LayeredMLP``):
      * ``names``: parameter names in FORWARD order;
      * ``place(name, arr)``: apply the harness's sharding/placement;
      * ``forward(params, x, y) -> ctx``;
      * ``backward(ctx, name) -> grad`` (called top layer first);
      * ``loss(ctx) -> float``.
    """

    def __init__(self, client, harness, overlap: bool = True,
                 window: int = 4):
        self.client = client
        self.harness = harness
        self.overlap = overlap
        self.window = max(1, window)
        self._params: Dict[str, object] = {}  # placed device arrays
        self._raw: Dict[str, object] = {}     # pulled, not yet placed
        self.versions: Dict[str, int] = {}    # last confirmed per name
        self._m = _metrics()
        self.last_stats: Optional[dict] = None
        self.last_trace = None  # RunTrace of the last SUCCESSFUL step
        self.totals = {"steps": 0, "wall_ms": 0.0, "compute_ms": 0.0,
                       "wire_busy_ms": 0.0, "exposed_comm_ms": 0.0,
                       "overlapped_comm_ms": 0.0}

    # ---- setup ----

    def prime(self) -> None:
        """Fetch the full parameter set once (the step-0 pull the
        overlap then amortizes into every later step's shadow)."""
        got = self.client.pull_all(list(self.harness.names),
                                   window=self.window)
        for name, (version, arr) in got.items():
            self._raw[name] = arr
            self.versions[name] = version

    def _note_push_error(self, e: "native.RpcError") -> None:
        """The push-side healing every other push path runs on RpcError
        (push_grad/push_all): overload answers feed the client's pacer,
        and an undecodable-push / pre-codec-rollback answer drops the
        stale codec advertisement so the NEXT step renegotiates (raw).
        The driver still surfaces THIS step's failure to the caller —
        healing changes what the retry sends, not whether this step
        failed. Fleet-shaped clients run both hooks inside their own
        push_grad, so the getattr guards just no-op there."""
        pacer = getattr(self.client, "pacer", None)
        if pacer is not None:
            pacer.note(e)
        heal = getattr(self.client, "_codec_push_failed", None)
        if heal is not None:
            heal(e)

    # ---- one step ----

    def step(self, x, y) -> float:
        """One training step; returns the loss. Overlapped mode pulls
        each parameter's NEXT version inside this step's shadow, so the
        next call starts compute immediately."""
        from brpc_tpu.observability import tracing

        import jax

        t0 = time.monotonic()
        pacer = getattr(self.client, "pacer", None)
        if pacer is not None:
            pacer.pace()  # honor any shed-storm retry-after debt
        names: List[str] = list(self.harness.names)
        rev = list(reversed(names))
        grads: Dict[str, object] = {}
        step_versions: Dict[str, int] = {}
        push_failed: Dict[str, BaseException] = {}
        ctx_box: Dict[str, object] = {}
        channel = getattr(self.client, "channel", None)

        def on_push_reply(tag, payload, view):
            view.release()  # push responses carry no tensor
            step_versions[tag] = int(payload.decode())

        win = (PipelineWindow(channel, self.window, on_reply=on_push_reply)
               if channel is not None else None)
        # PipelineWindow.submit counts no bytes itself — the push_all
        # discipline: account per submit so the flagship loop's push
        # volume shows on /vars like every other push path (the fleet
        # path counts inside push_device).
        from brpc_tpu.runtime.tensor import _metrics as _tensor_metrics
        push_bytes = _tensor_metrics()["push_bytes"]

        def traced(span_name, fn):
            def run(done):
                with tracing.trace_span(span_name):
                    return fn(done)
            return run

        def fn_forward(done):
            for name, arr in self._raw.items():
                self._params[name] = self.harness.place(name, arr)
            self._raw.clear()
            ctx_box["ctx"] = self.harness.forward(self._params, x, y)
            return None

        def make_bwd(name):
            def fn(done):
                g = self.harness.backward(ctx_box["ctx"], name)
                # Materialize here so compute time is attributed to the
                # compute lane (and the wire lane's staging D2H reads a
                # finished array instead of blocking on dispatch).
                grads[name] = jax.block_until_ready(g)
                return None
            return fn

        def drain_one_recording() -> bool:
            """One complete_one() with per-tag failure recording — the
            single home of the drain discipline (opt nodes, full-window
            pre-drain, and the post-run late drain all ride it), so a
            failed reply is always attributed to ITS tag and the stale-
            advertisement / pacer healing hooks always run."""
            try:
                return win.complete_one()
            except Exception as e:  # noqa: BLE001 — ANY reply failure
                # (RpcError or a malformed-payload decode error from
                # on_push_reply) belongs to the tag that produced it,
                # never to whichever innocent node happened to drain.
                tag = getattr(e, "pipeline_tag", None)
                push_failed.setdefault(tag if tag is not None else "?", e)
                if isinstance(e, native.RpcError):
                    self._note_push_error(e)
                return True

        def make_push(name):
            if win is not None:
                def fn(done):
                    # Drain a full window HERE (recording per tag), not
                    # inside submit: submit's internal drain raises an
                    # EARLIER push's reply error untagged out of THIS
                    # node, failing an innocent layer and cancelling its
                    # salvageable push.
                    while win.inflight() >= win.window:
                        if not drain_one_recording():
                            break
                    enc = self.client._grad_encoder(name)
                    if enc is not None:
                        enc = _staged_encode(enc)
                    win.submit("ParamService/Push", array=grads[name],
                               request=name.encode(), tag=name,
                               encoder=enc)
                    push_bytes.add(int(getattr(grads[name], "nbytes", 0)))
                    return None
            else:
                def fn(done):
                    step_versions[name] = self.client.push_grad(
                        name, grads[name])
                    return None
            return fn

        def make_opt(name):
            def fn(done):
                # Drain the window until THIS push's reply lands (the
                # server applied its momentum step and bumped the
                # version) — earlier-submitted replies deliver on the
                # way, later pushes stay in flight. A failed drain is
                # recorded against the tag it belongs to, so one bad
                # push never mis-attributes its neighbours.
                while (name not in step_versions
                       and name not in push_failed and win is not None):
                    if not drain_one_recording():
                        break
                if name in step_versions:
                    self.versions[name] = step_versions[name]
                    return step_versions[name]
                err = push_failed.get(name)
                if err is None:
                    err = native.RpcError(
                        native.TRPC_EEOF,
                        f"push reply for {name} never arrived")
                raise err
            return fn

        def make_pull(name):
            def fn(done):
                version, arr = self.client.pull(name)
                self._raw[name] = arr
                self.versions[name] = version
                return version
            return fn

        graph = StepGraph()
        # Insertion order IS the serial schedule: forward, every
        # backward, every push, every confirm, every pull — today's
        # driver exactly when overlap=False.
        graph.add("fwd", traced("step/fwd", fn_forward), lane=COMPUTE)
        prev = "fwd"
        for name in rev:
            prev = graph.add(f"bwd:{name}",
                             traced(f"step/bwd:{name}", make_bwd(name)),
                             deps=(prev,), lane=COMPUTE)
        for name in rev:
            graph.add(f"push:{name}",
                      traced(f"step/push:{name}", make_push(name)),
                      deps=(f"bwd:{name}",), lane=WIRE)
        for name in rev:
            graph.add(f"opt:{name}",
                      traced(f"step/opt:{name}", make_opt(name)),
                      deps=(f"push:{name}",), lane=WIRE)
        for name in rev:
            graph.add(f"pull:{name}",
                      traced(f"step/pull:{name}", make_pull(name)),
                      deps=(f"opt:{name}",), lane=WIRE)

        failure: Optional[StepFailure] = None
        trace = None
        with tracing.trace_span("train_step"):
            tid, sid = tracing.current_trace()
            wire_ctx = _trace_handoff_ctx(
                tid, sid, qos=getattr(self.client, "_qos_bulk", None))

            try:
                _results, trace = run_graph(graph, overlap=self.overlap,
                                            wire_ctx=wire_ctx)
            except StepFailure as sf:
                failure = sf
            except BaseException:
                # Ctrl-C and friends: the scheduler aborted promptly —
                # do NOT drain in-flight replies here (each blocks up
                # to the channel timeout, and this path never uses the
                # salvage data). Cancel and free the staged window
                # instead; the wire thread is joined, so no concurrent
                # access.
                if win is not None:
                    win.abort()
                raise
            # Late replies still count: a push whose confirm was
            # cancelled may have landed server-side — drain the window
            # so `applied` is accurate before salvage math (the wire
            # thread is joined; no concurrent access).
            if win is not None:
                while drain_one_recording():
                    pass
            if failure is not None:
                # The success path's pulls already recorded NEWER
                # versions per name — only the failure path needs the
                # late-drained confirms merged (never backwards).
                for name, v in step_versions.items():
                    self.versions[name] = max(
                        self.versions.get(name, 0), v)
            if trace is not None:
                wall_ms = trace.wall_s * 1e3
                exposed_ms = trace.exposed_wait_s * 1e3
                overlapped_ms = trace.overlapped_comm_s() * 1e3
                tracing.annotate(
                    f"exposed_comm={int(exposed_ms * 1e3)}us")
                tracing.annotate(
                    f"overlapped_comm={int(overlapped_ms * 1e3)}us")
                tracing.annotate(
                    f"compute={int(trace.compute_busy_s * 1e6)}us")

        if failure is not None:
            raise self._salvage(failure, names, step_versions, push_failed)

        if pacer is not None:
            pacer.clear()  # a whole step landed: the server is admitting
        loss = float(self.harness.loss(ctx_box["ctx"]))
        stats = {
            "loss": loss, "overlap": self.overlap,
            "wall_ms": wall_ms,
            "compute_ms": trace.compute_busy_s * 1e3,
            "wire_busy_ms": trace.wire_busy_s * 1e3,
            "exposed_comm_ms": exposed_ms,
            "overlapped_comm_ms": overlapped_ms,
        }
        self.last_stats = stats
        self.last_trace = trace
        self.totals["steps"] += 1
        for k in ("wall_ms", "compute_ms", "wire_busy_ms",
                  "exposed_comm_ms", "overlapped_comm_ms"):
            self.totals[k] += stats[k]
        self._m["steps"].add(1)
        self._m["step"].record_s(time.monotonic() - t0)
        self._m["exposed"].record_us(int(exposed_ms))      # ms samples
        self._m["overlapped"].record_us(int(overlapped_ms))  # ms samples
        return loss

    def _salvage(self, sf: StepFailure, names, step_versions,
                 push_failed) -> BaseException:
        """Map a StepFailure onto the per-name push salvage contract."""
        wire_fail = {n: e for n, e in sf.failed.items()
                     if n.startswith(("push:", "opt:"))}
        if not wire_fail:
            # Compute- or pull-side failure: nothing ambiguous about the
            # pushes (they all confirmed or never started) — surface the
            # original cause. Not a PARTIAL-push failure, so the
            # counter stays put (operators alert on it).
            return sf.cause
        self._m["partial"].add(1)
        cause = None
        for e in list(wire_fail.values()) + list(push_failed.values()):
            if isinstance(e, native.RpcError):
                cause = e
                break
        if cause is None:
            cause = native.RpcError(native.TRPC_EEOF, str(next(iter(
                wire_fail.values()))))
        unpushed = [n for n in names if n not in step_versions]
        err = PartialPushError(cause, dict(step_versions), unpushed)
        err.step_failure = sf
        return err

    def run(self, batches) -> List[float]:
        """Convenience loop: ``batches`` yields ``(x, y)`` pairs."""
        return [self.step(x, y) for x, y in batches]


class CollectiveStepDriver:
    """Data-parallel training where the gradient exchange is a ring
    allreduce over a :class:`~brpc_tpu.collectives.group.CollectiveGroup`
    instead of N point-to-point pushes into a parameter server (ISSUE
    13): every member holds the full parameter set locally, computes
    gradients on its own batch shard, and each layer's exchange is an
    ``allreduce:k`` node scheduled on a NAMED wire lane —

        forward -> bwd:k (compute lane, top layer first)
        bwd:k   -> allreduce:k (lane ``wire:ar<k % wire_lanes>``)
        allreduce:k -> opt:k (compute lane: the local momentum update)

    A collective hop BLOCKS waiting for its ring predecessor, so one
    wire thread would serialize layer k+1's collective behind layer k's
    waits; per-peer wire lanes (the :mod:`step_sched` generalization —
    PR 12's named leftover) let reduction hops of layer k hide behind
    layer k+1's backward AND behind each other. ``overlap=False`` runs
    the same nodes serially — the A/B baseline.

    The optimizer is ONE jitted ``fused_momentum_update`` call per layer
    over the reduced buffer (the PR 13 leftover retired): the auto-routed
    Pallas kernel on TPU, the identical jnp reference elsewhere —
    trajectory parity with the explicit momentum formula is pinned, and
    the copy-on-write discipline (handed-out arrays stay immutable) is
    unchanged; ``ef=False`` on the group is the naive-requantizer
    negative control the convergence tests pin.

    Failure: a hop failure (member left, timeout) cancels exactly that
    layer's ``opt:k`` while every other layer completes (partial
    salvage across lanes); the step raises the triggering
    :class:`~brpc_tpu.collectives.core.CollectiveAborted` with the full
    graph post-mortem on ``.step_failure`` — the caller re-``sync()``\\ s
    the group and resumes on the surviving ring.

    ``track=True`` — T3 track-and-trigger (ISSUE 20, arXiv 2401.16677):
    instead of an ``opt:k`` node that waits for layer k's WHOLE
    allreduce, the momentum update rides the collective's per-chunk
    finality hook (``on_chunk``) — each reduced span is applied the
    moment it lands, while later chunks of the same layer are still on
    the wire, so by op completion the optimizer is already done and the
    op-completion ``opt:k`` nodes vanish from the graph. The per-chunk
    update is deliberately NUMPY (the param-server formula ``m' =
    beta*m + g; p' = p - lr*m'``), not the jitted fused kernel: the
    trigger fires on a WIRE lane, and jax dispatch off the caller's
    thread is the PR 6 contention class (now a tpulint finding —
    ``regime-graph``). Trajectory: chunkwise-numpy == whole-array-numpy
    exactly (elementwise math over a partition); numpy-vs-fused parity
    carries the usual fp32 tolerance, pinned in tests. The delta shows
    in ``RunTrace``: the compute lane no longer stalls on, or joins
    behind, tail-layer optimizer waits (``exposed_stall_s`` /
    ``exposed_join_s``).
    """

    def __init__(self, group, harness, overlap: bool = True,
                 wire_lanes: int = 2, lr: float = 0.01,
                 momentum: float = 0.9, average: bool = True,
                 track: bool = False):
        self.group = group
        self.harness = harness
        self.overlap = overlap
        self.wire_lanes = max(1, wire_lanes)
        self.lr = lr
        self.momentum = momentum
        self.average = average
        self.track = track
        self._params: Dict[str, object] = {}   # numpy fp32 masters
        self._momenta: Dict[str, object] = {}
        self._m = _metrics()
        self.last_stats: Optional[dict] = None
        self.last_trace = None
        # track mode: {name: [(chunk_idx, (offset, length)), ...]} of the
        # last step, in firing order — the tests' view of the trigger.
        self.last_chunk_log: Dict[str, list] = {}
        self.totals = {"steps": 0, "wall_ms": 0.0, "compute_ms": 0.0,
                       "wire_busy_ms": 0.0, "exposed_comm_ms": 0.0,
                       "overlapped_comm_ms": 0.0}

    def prime(self, params: Optional[Dict[str, object]] = None) -> None:
        """Adopt the initial parameter set (fp32 numpy masters). All
        members must start identical — ``harness.init_params()`` is
        deterministic per seed, so calling this with the default on
        every member satisfies that."""
        import numpy as np

        src = params if params is not None else self.harness.init_params()
        for name in self.harness.names:
            self._params[name] = np.array(np.asarray(src[name]),
                                          dtype=np.float32)
            self._momenta[name] = np.zeros_like(self._params[name])

    def params(self) -> Dict[str, object]:
        return dict(self._params)

    def step(self, x, y) -> float:
        from brpc_tpu.observability import tracing

        import jax
        import jax.numpy as jnp
        import numpy as np

        t0 = time.monotonic()
        names: List[str] = list(self.harness.names)
        rev = list(reversed(names))
        world = max(1, self.group.world)
        grads: Dict[str, object] = {}
        reduced: Dict[str, object] = {}
        ctx_box: Dict[str, object] = {}

        def traced(span_name, fn):
            def run(done):
                with tracing.trace_span(span_name):
                    return fn(done)
            return run

        def fn_forward(done):
            placed = {n: self.harness.place(n, self._params[n])
                      for n in names}
            ctx_box["ctx"] = self.harness.forward(placed, x, y)
            return None

        def make_bwd(name):
            def fn(done):
                g = self.harness.backward(ctx_box["ctx"], name)
                grads[name] = jax.block_until_ready(g)
                return None
            return fn

        def make_allreduce(name):
            def fn(done):
                g = np.asarray(grads[name])  # D2H on the wire lane
                red = self.group.allreduce(name, g)
                if self.average:
                    red /= np.float32(world)
                reduced[name] = red
                return None
            return fn

        def make_allreduce_tracked(name):
            def fn(done):
                g = np.asarray(grads[name])  # D2H on the wire lane
                shape = np.shape(self._params[name])
                # Copy-on-write: update fresh flats, install when the op
                # lands — handed-out arrays stay immutable, and a failed
                # op leaves params/momenta untouched.
                pf = np.array(self._params[name],
                              dtype=np.float32).reshape(-1)
                mf = np.array(self._momenta[name],
                              dtype=np.float32).reshape(-1)
                chunk_log = self.last_chunk_log.setdefault(name, [])
                chunk_log.clear()
                inv = np.float32(1.0 / world)

                def on_chunk(idx, span, vals):
                    # Numpy on purpose — this runs on a WIRE lane (see
                    # class docstring / the regime-graph lint rule).
                    off, ln = span
                    gc = vals * inv if self.average else vals
                    mf[off:off + ln] = (np.float32(self.momentum)
                                        * mf[off:off + ln] + gc)
                    pf[off:off + ln] -= np.float32(self.lr) \
                        * mf[off:off + ln]
                    chunk_log.append((idx, span))

                red = self.group.allreduce(name, g, on_chunk=on_chunk)
                if self.average:
                    red /= np.float32(world)
                reduced[name] = red
                self._momenta[name] = mf.reshape(shape)
                self._params[name] = pf.reshape(shape)
                return None
            return fn

        def make_opt(name):
            def fn(done):
                # ONE jitted fused-momentum-update call over the reduced
                # buffer (the PR 13 leftover): ops/fused_momentum_update
                # auto-routes — the Pallas kernel on TPU (one HBM round
                # trip for the whole (p, m, g) -> (p', m') update), the
                # identical jnp math elsewhere. Copy-on-write discipline
                # preserved: handed-out arrays stay immutable, the
                # detached results replace them.
                from brpc_tpu.ops.fused_update import fused_momentum_update

                p2, m2 = fused_momentum_update(
                    jnp.asarray(self._params[name]),
                    jnp.asarray(self._momenta[name]),
                    jnp.asarray(reduced[name]),
                    lr=self.lr, beta=self.momentum)
                p2, m2 = jax.block_until_ready((p2, m2))
                self._momenta[name] = np.asarray(m2)
                self._params[name] = np.asarray(p2)
                return None
            return fn

        graph = StepGraph()
        graph.add("fwd", traced("step/fwd", fn_forward), lane=COMPUTE)
        prev = "fwd"
        for name in rev:
            prev = graph.add(f"bwd:{name}",
                             traced(f"step/bwd:{name}", make_bwd(name)),
                             deps=(prev,), lane=COMPUTE)
        mk_ar = make_allreduce_tracked if self.track else make_allreduce
        for k, name in enumerate(rev):
            graph.add(f"allreduce:{name}",
                      traced(f"step/allreduce:{name}", mk_ar(name)),
                      deps=(f"bwd:{name}",),
                      lane=f"wire:ar{k % self.wire_lanes}")
        if not self.track:
            # Track mode has no opt nodes: the momentum update already
            # happened per chunk inside each allreduce as spans landed.
            for name in rev:
                graph.add(f"opt:{name}",
                          traced(f"step/opt:{name}", make_opt(name)),
                          deps=(f"allreduce:{name}",), lane=COMPUTE)

        with tracing.trace_span("train_step"):
            tid, sid = tracing.current_trace()
            # No qos factory: the collective stamps its own BULK QoS
            # per peer inside the hop sends.
            wire_ctx = _trace_handoff_ctx(tid, sid)

            try:
                _results, trace = run_graph(graph, overlap=self.overlap,
                                            wire_ctx=wire_ctx)
            except StepFailure as sf:
                self._m["partial"].add(1)
                cause = sf.cause
                try:
                    cause.step_failure = sf
                except Exception:  # noqa: BLE001 — exotic exception
                    pass
                raise cause
            wall_ms = trace.wall_s * 1e3
            exposed_ms = trace.exposed_wait_s * 1e3
            overlapped_ms = trace.overlapped_comm_s() * 1e3
            tracing.annotate(f"exposed_comm={int(exposed_ms * 1e3)}us")
            tracing.annotate(
                f"overlapped_comm={int(overlapped_ms * 1e3)}us")

        loss = float(self.harness.loss(ctx_box["ctx"]))
        stats = {
            "loss": loss, "overlap": self.overlap,
            "wall_ms": wall_ms,
            "compute_ms": trace.compute_busy_s * 1e3,
            "wire_busy_ms": trace.wire_busy_s * 1e3,
            "exposed_comm_ms": exposed_ms,
            "overlapped_comm_ms": overlapped_ms,
        }
        self.last_stats = stats
        self.last_trace = trace
        self.totals["steps"] += 1
        for k in ("wall_ms", "compute_ms", "wire_busy_ms",
                  "exposed_comm_ms", "overlapped_comm_ms"):
            self.totals[k] += stats[k]
        self._m["steps"].add(1)
        self._m["step"].record_s(time.monotonic() - t0)
        self._m["exposed"].record_us(int(exposed_ms))      # ms samples
        self._m["overlapped"].record_us(int(overlapped_ms))  # ms samples
        return loss

    def run(self, batches) -> List[float]:
        return [self.step(x, y) for x, y in batches]


def _staged_encode(enc):
    """Wrap a gradient encoder so its quantize cost shows as an
    ``encode`` stage on the push node's span — running at arena-stage
    time on the wire lane, i.e. inside the next layer's compute shadow
    (the PR 7 quantize-at-stage hook riding the overlap for free)."""
    from brpc_tpu.observability import tracing

    def run(host):
        with tracing.stage("encode"):
            return enc(host)
    return run
