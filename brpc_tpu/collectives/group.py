"""CollectiveGroup — ring/tree collectives over the fleet's tensor wire.

Membership IS the registry (the ShardMap discipline): every member runs
a ``CollectiveService`` on its own native server, registers the address
under one tag, and derives the SAME ring order from the sorted
membership list — ``sync()`` freezes a ``(members, epoch)`` pair (epoch
= the registry's membership index), and every chunk on the wire is
stamped with that epoch so two members that froze different rings fail
fast (E_COLL_EPOCH) instead of mis-reducing.

Each hop is a chunked transfer over a per-peer ``TensorChannel`` +
``PipelineWindow``: the sender frames the hop's chunk(s) with the
groupwire manifest (the PushQ shape — per-chunk metadata, concatenated
payload runs), stamps BULK QoS after the peer's Hello advertised it
(the codec-negotiation discipline), and paces on overload answers (the
OverloadPacer brake; a paced retry is safe because mailbox deposits are
idempotent). Quantization rides ``quant.ChunkCodec`` per chunk per hop
— dequant -> reduce -> requant with per-block scales and error-feedback
accumulators preserved across reduction steps (EQuARX, PAPERS.md) —
negotiated per PEER via Hello, so a mixed ring degrades hop by hop, raw
included, while the self-describing metadata keeps every decode honest.

Failure is clean, never wedged: a member leaving mid-collective is
detected by the registry watch (or a dead-peer transport error) and the
op raises :class:`~brpc_tpu.collectives.core.MemberLeft` carrying the
per-chunk salvage (``.done``); the caller re-``sync()``\\ s and retries
on the surviving ring. One rpcz trace per collective: the op opens a
root span and every chunk RPC parents under it, fleet-assembled like a
pull_all.
"""

from __future__ import annotations

import json
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from brpc_tpu.collectives import core, ring as ring_mod
from brpc_tpu.collectives.quant import ChunkCodec
from brpc_tpu.fleet import registry
from brpc_tpu.observability import tracing
from brpc_tpu.runtime import codec as codec_mod
from brpc_tpu.runtime import groupwire, native
from brpc_tpu.runtime.param_server import E_NO_SUCH, OverloadPacer
from brpc_tpu.runtime.tensor import (PipelineWindow, TensorArena,
                                     TensorChannel, add_tensor_service)

E_COLL_EPOCH = core.E_COLL_EPOCH


def _native_available() -> bool:
    try:
        native.lib()
        return True
    except Exception:  # noqa: BLE001 — no lib and no toolchain
        return False


_metrics_cache = None


def collective_metrics():
    """Process-wide collective recorders — native tbvar series (they ride
    /vars, /brpc_metrics and every /fleetz scrape through the generic
    fold, no special-casing), no-op shims without the native library."""
    global _metrics_cache
    if _metrics_cache is None:
        if _native_available():
            from brpc_tpu.observability import metrics as obs

            _metrics_cache = {
                "allreduce": obs.latency("collective_allreduce"),
                "allgather": obs.latency("collective_allgather"),
                "reduce_scatter": obs.latency("collective_reduce_scatter"),
                "broadcast": obs.latency("collective_broadcast"),
                "ops": obs.counter("collective_ops"),
                "aborts": obs.counter("collective_aborts"),
                # Logical vs wire: the quantized-collective bandwidth win
                # reads straight off these two, like tensor_codec_*.
                "logical_bytes": obs.counter("collective_logical_bytes"),
                "wire_bytes": obs.counter("collective_wire_bytes"),
            }
        else:
            from brpc_tpu.observability.metrics import NullSeries

            _metrics_cache = {k: NullSeries() for k in (
                "allreduce", "allgather", "reduce_scatter", "broadcast",
                "ops", "aborts", "logical_bytes", "wire_bytes")}
    return _metrics_cache


class _RpcLink:
    """One op's view of the wire: per-destination PipelineWindow over the
    group's per-thread channels, groupwire-framed sends, mailbox recv."""

    def __init__(self, group: "CollectiveGroup", op: str, seq: int,
                 deadline: float):
        self.g = group
        self.op = op
        self.seq = seq
        self.deadline = deadline
        self._wins: Dict[str, PipelineWindow] = {}
        self._chans: Dict[str, TensorChannel] = {}  # checked out per op
        # Fragment payloads still in flight, per destination, keyed by
        # the fragment's (phase, step, frag) tag: an overload error from
        # the window belongs to the OLDEST in-flight fragment (submit
        # drains before staging), so the retry must resend THAT
        # fragment's bytes, not whatever the caller is currently
        # sending. Entries drop as acks drain.
        self._inflight: Dict[str, Dict[tuple, tuple]] = {}
        self.wire_bytes = 0

    def _chan(self, addr: str) -> TensorChannel:
        ch = self._chans.get(addr)
        if ch is None:
            ch = self._chans[addr] = self.g._checkout(addr)
        return ch

    def _window(self, addr: str) -> PipelineWindow:
        win = self._wins.get(addr)
        if win is None:
            pending = self._inflight.setdefault(addr, {})

            def on_reply(tag, _payload, view, _p=pending):
                view.release()
                _p.pop(tag, None)

            win = PipelineWindow(self._chan(addr), self.g.window,
                                 on_reply=on_reply)
            self._wins[addr] = win
        return win

    def _resend_paced(self, addr: str, tag: tuple,
                      first_err: "native.RpcError") -> None:
        """Redeliver one shed fragment directly (outside the window),
        paced on the server's retry-after hints — mailbox deposits are
        idempotent, so resending a frame that DID land is safe."""
        manifest, concat = self._inflight[addr][tag]
        self.g.pacer.note(first_err)
        while True:
            if time.monotonic() >= self.deadline:
                raise core.CollectiveTimeout("timeout (overloaded peer)",
                                             tag[0], tag[1])
            self.g.pacer.pace()
            try:
                with self.g._qos_for(addr):
                    self._chan(addr).call(
                        "CollectiveService/Chunk",
                        array=concat if concat.nbytes else None,
                        request=manifest)
                self.g.pacer.clear()
                self._inflight[addr].pop(tag, None)
                return
            except native.RpcError as e:
                if not e.overloaded:
                    raise self.g._map_rpc_error(e, tag[0], tag[1])
                self.g.pacer.note(e)

    def send(self, dst_rank: int, phase: str, step: int, idx: int,
             meta: dict, blob: np.ndarray, frag: int = 0,
             nfrags: int = 1) -> None:
        addr = self.g._members[dst_rank]
        entry = dict(meta, idx=int(idx))
        manifest, concat = groupwire.pack_group(
            [entry], [blob],
            extra={"op": self.op, "seq": self.seq, "ph": phase,
                   "step": int(step), "frag": int(frag),
                   "ep": self.g._epoch, "src": self.g.rank})
        self.wire_bytes += int(concat.nbytes)
        if self.g.emulate_wire_gbps:
            # Bench-only link emulation: serialize this fragment's BYTES
            # through a modeled uplink (loopback shm runs at memcpy
            # speed, which no real cross-host fleet link does — this is
            # how the wire-BOUND regime is measured on a one-box CI).
            time.sleep(  # tpulint: allow(py-blocking)
                concat.nbytes / (self.g.emulate_wire_gbps * 1e9))
        win = self._window(addr)
        tag = (phase, int(step), int(frag))
        self._inflight[addr][tag] = (manifest, concat)
        while True:
            try:
                with self.g._qos_for(addr):
                    win.submit("CollectiveService/Chunk",
                               array=concat if concat.nbytes else None,
                               request=manifest, tag=tag)
                return
            except native.RpcError as e:
                if not e.overloaded:
                    raise self.g._map_rpc_error(e, phase, step)
                # Shed-before-queue answer from draining the OLDEST
                # in-flight fragment (its tag rides e.pipeline_tag):
                # redeliver THOSE bytes paced, then resubmit the
                # current fragment (still staged in _inflight, never
                # accepted by the window when submit raised).
                shed = getattr(e, "pipeline_tag", None)
                if shed is None or shed not in self._inflight[addr]:
                    shed = tag
                self._resend_paced(addr, shed, e)
                if shed == tag:
                    return

    _DRAIN_GRACE_S = 2.5  # stall time before draining our own window

    def recv(self, phase: str, step: int,
             frag: int = 0) -> Tuple[int, dict, np.ndarray]:
        # A fragment we SENT can fail while we sit here — the window is
        # async and submit only drains when full, so without this the
        # error (shed, dead peer, mismatched ring) stays invisible and
        # both sides of the ring stall until op timeout. A recv that
        # waits past the grace drains its own outbound window: failures
        # surface now (shed fragments redeliver paced, anything else
        # aborts the op promptly), and a healthy-but-slow wire just pays
        # one flush on an already-stalled path.
        key = (self.op, self.seq, phase, int(step), int(frag))
        while True:
            slice_dl = min(time.monotonic() + self._DRAIN_GRACE_S,
                           self.deadline)
            try:
                return self.g._mailbox.take(key, slice_dl,
                                            abort_event=self.g._left)
            except core.CollectiveTimeout:
                if time.monotonic() >= self.deadline:
                    raise
            self._drain_stalled()

    def _drain_stalled(self) -> None:
        for addr, win in list(self._wins.items()):
            while win.inflight():
                try:
                    win.flush()
                except native.RpcError as e:
                    if not e.overloaded:
                        raise self.g._map_rpc_error(e, "drain", -1)
                    shed = getattr(e, "pipeline_tag", None)
                    if shed is not None and \
                            shed in self._inflight.get(addr, {}):
                        self._resend_paced(addr, shed, e)
                    else:
                        self.g.pacer.note(e)

    def close(self, ok: bool) -> None:
        try:
            for addr, win in self._wins.items():
                while True:
                    try:
                        if ok:
                            win.flush()
                        else:
                            win.abort()
                        break
                    except native.RpcError as e:
                        if not (ok and e.overloaded):
                            if ok:
                                raise
                            break
                        # A shed surfacing at the end-of-op flush is
                        # the same overload as mid-op: redeliver that
                        # fragment paced, keep draining the rest.
                        shed = getattr(e, "pipeline_tag", None)
                        if shed is not None and shed in \
                                self._inflight.get(addr, {}):
                            self._resend_paced(addr, shed, e)
                        else:
                            self.g.pacer.note(e)
        finally:
            self._wins.clear()
            self._inflight.clear()
            chans, self._chans = self._chans, {}
            for addr, ch in chans.items():
                self.g._checkin(addr, ch)


class CollectiveGroup:
    """One member of a registry-defined collective ring.

    ``codec="int8"`` (or ``"fp8e4m3"``) quantizes every hop against
    peers that advertise it; ``ef=False`` is the naive requantizer (the
    pinned negative control — linearly compounding error, bench/test
    only). ``tree_max_bytes`` routes tensors at or below it through the
    2-hop tree instead of the 2(n-1)-hop ring (the latency/bandwidth
    crossover for small tensors)."""

    def __init__(self, registry_hostport: str, tag: str = "collective",
                 listen: str = "127.0.0.1:0", codec: Optional[str] = None,
                 ef: bool = True, block: int = codec_mod.DEFAULT_BLOCK,
                 window: int = 4, op_timeout_s: float = 20.0,
                 tree_max_bytes: int = 64 << 10,
                 frag_bytes: int = 1 << 20,
                 arena_bytes: int = 64 << 20,
                 client_arena_bytes: int = 32 << 20,
                 ttl_s: int = 5, tenant: str = "",
                 emulate_wire_gbps: Optional[float] = None,
                 name: Optional[str] = None):
        self._registry = registry_hostport
        self.tag = tag
        self.window = max(1, window)
        self.op_timeout_s = op_timeout_s
        self.tree_max_bytes = tree_max_bytes
        # ~1MB wire fragments measured fastest on this transport (bigger
        # single attachments LOSE throughput — 8MB monoliths ran ~0.5x).
        self.frag_elems = max(1, frag_bytes // 4)
        # Bench-only: emulate a bounded cross-host link (GB/s) by
        # serializing each fragment's wire bytes at the sender. None =
        # the real transport. Never set in production paths.
        self.emulate_wire_gbps = emulate_wire_gbps
        self._client_arena_bytes = client_arena_bytes
        self._tenant = tenant
        self._codec_name = codec
        self.chunk_codec = ChunkCodec(ef=ef, block=block)
        self.name = name
        self._m = collective_metrics()
        self.pacer = OverloadPacer()

        self.server = native.Server()
        self.arena = add_tensor_service(self.server, "CollectiveService",
                                        self._handle,
                                        TensorArena(arena_bytes))
        port = self.server.start(listen)
        host = listen.rsplit(":", 1)[0] or "127.0.0.1"
        self.addr = f"{host}:{port}"

        self._mailbox = core.Mailbox()
        self._mu = threading.Lock()
        self._presync: List[tuple] = []  # chunks held before sync()
        self._members: Tuple[str, ...] = ()
        self._epoch: Optional[int] = None
        self.rank: Optional[int] = None
        self._left = threading.Event()
        self.left_members: List[str] = []
        self._seq: Dict[str, int] = {}
        self._peer_caps: Dict[str, dict] = {}
        # Per-peer channel CHECKOUT pool (not per-thread: the step
        # driver's wire-lane threads are fresh every step, and a
        # thread-keyed cache would mint a new channel + client arena
        # per lane per step — a ~32MB native leak per step). An op
        # checks a channel out for its duration and returns it; the
        # pool high-water mark is the number of concurrent ops per
        # peer (the driver's wire_lanes).
        self._chan_pool: Dict[str, List[TensorChannel]] = {}
        self._closed = False

        self._reg = registry.Registration(registry_hostport, self.addr,
                                          tag, ttl_s).start()
        self._watcher = registry.RegistryWatcher(
            registry_hostport, tag, self._on_membership).start()

    # ---- membership / ring wiring ----

    def _on_membership(self, _index: int, addrs: List[str]) -> None:
        with self._mu:
            if not self._members:
                return
            gone = [a for a in self._members if a not in addrs]
            if gone:
                self.left_members = gone
                self._left.set()

    def sync(self, expect: Optional[int] = None,
             timeout_s: float = 10.0) -> int:
        """Freeze the ring at the current registry membership: returns
        this member's rank. ``expect`` waits (bounded) until exactly that
        many members are registered — the barrier every member calls
        before the first collective (and after a membership edge)."""
        deadline = time.monotonic() + timeout_s
        while True:
            _index, addrs = registry.list_servers(self._registry, self.tag)
            members = tuple(ring_mod.ring_order(addrs))
            if self.addr in members and (expect is None
                                         or len(members) == expect):
                break
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"sync: registry shows {len(members)} member(s) "
                    f"{list(members)}, want "
                    f"{'self registered' if expect is None else expect}")
            time.sleep(0.05)
        with self._mu:
            if members != self._members:
                # Ring roles shift with membership: every hop-position
                # residual keys to the OLD ring — drop them all (costing
                # at most one quant step per position on streams that
                # just ended) rather than compensate the wrong chunk.
                self.chunk_codec.prune(lambda _k: False)
            self._members = members
            # The epoch is a digest of the membership CONTENT, not the
            # registry's version counter: that counter is global across
            # tags, so two members listing the SAME ring at different
            # moments (another group registering in between) would
            # freeze different numbers and reject each other's chunks.
            # Same sorted member list => same ring => same epoch, on
            # every member, with no coordination.
            self._epoch = zlib.crc32("|".join(members).encode())
            self.rank = members.index(self.addr)
            self._left.clear()
            self.left_members = []
            held, self._presync = self._presync, []
        if held:
            self._replay_presync(held)
        return self.rank

    @property
    def members(self) -> Tuple[str, ...]:
        return self._members

    @property
    def epoch(self) -> Optional[int]:
        return self._epoch

    @property
    def world(self) -> int:
        return len(self._members)

    # ---- per-peer plumbing ----

    def _checkout(self, addr: str) -> TensorChannel:
        with self._mu:
            if self._closed:
                raise RuntimeError("collective group is closed")
            pool = self._chan_pool.get(addr)
            if pool:
                return pool.pop()
        return TensorChannel(f"tpu://{addr}",
                             TensorArena(self._client_arena_bytes),
                             timeout_ms=int(self.op_timeout_s * 1000))

    def _checkin(self, addr: str, ch: TensorChannel) -> None:
        with self._mu:
            if not self._closed:
                self._chan_pool.setdefault(addr, []).append(ch)
                return
        ch.close()  # group closed while we held it

    def _caps(self, addr: str) -> dict:
        with self._mu:
            caps = self._peer_caps.get(addr)
        if caps is not None:
            return caps
        cache = True
        ch = self._checkout(addr)
        try:
            payload, _ = ch.call("CollectiveService/Hello")
            caps = json.loads(payload.decode())
        except native.RpcError as e:
            # A pre-collective peer answers "no such method"
            # DETERMINISTICALLY — cache the raw/unstamped degrade. A
            # transport/overload failure is transient: serve degraded
            # caps for THIS call but retry the Hello next time, or a
            # startup hiccup would silently cost the peer its codec and
            # QoS stamp for the group's whole lifetime.
            caps = {"qos": 0, "codecs": []}
            cache = e.code == E_NO_SUCH  # genuinely pre-collective
        except ValueError:
            caps = {"qos": 0, "codecs": []}  # malformed Hello: cache —
        finally:                             # a rebuild won't fix bytes
            self._checkin(addr, ch)
        if cache:
            with self._mu:
                self._peer_caps[addr] = caps
        return caps

    def _qos_for(self, addr: str):
        import contextlib

        if self._caps(addr).get("qos"):
            return native.qos(native.PRIORITY_BULK, self._tenant)
        return contextlib.nullcontext()

    def _codec_for(self, addr: str) -> Optional[str]:
        """Per-peer negotiation (the Meta-advertisement discipline): the
        requested codec only if this peer's Hello advertised it."""
        return codec_mod.choose(self._codec_name,
                                tuple(self._caps(addr).get("codecs", ())))

    def _ring_codec(self, members) -> Optional[str]:
        """Ring-wide negotiation: allgather-phase fragments are encoded
        ONCE and forwarded VERBATIM around the whole ring, so the codec
        engages only when EVERY other member advertised it — a
        successor-only handshake would forward bytes a later hop cannot
        decode (mixed-build rollout). Caps are cached per peer, so a
        warm ring costs no RPCs here."""
        if self._codec_name is None:
            return None
        for peer in members:
            if peer != self.addr and self._codec_for(peer) is None:
                return None
        return self._codec_name

    def _map_rpc_error(self, e: "native.RpcError", phase: str,
                       step: int) -> core.CollectiveAborted:
        if e.code == core.E_COLL_EPOCH:
            return core.CollectiveAborted(f"epoch: {e.text}", phase, step)
        # Transport-shaped errors against a frozen member usually mean it
        # died before the registry TTL noticed; surface as MemberLeft so
        # the caller's recovery path (re-sync, retry) is uniform.
        return core.MemberLeft(f"peer error: [{e.code}] {e.text}",
                               phase, step)

    # ---- service handler (runs on the callback pool) ----

    def _handle(self, method: str, request: bytes, att):
        if method == "Hello":
            return json.dumps(
                {"qos": 1, "codecs": list(codec_mod.supported_codecs()),
                 "addr": self.addr}).encode(), None
        if method == "Chunk":
            man = groupwire.parse_group(request)
            with self._mu:
                epoch = self._epoch
                hold = epoch is None
            if hold:
                # Pre-sync: this member registered (so peers can resolve
                # it) but hasn't frozen its ring yet — a faster peer's
                # first send can land in that window at every phase/ring
                # boundary. Rejecting would deadlock the ring until op
                # timeout (the sender's window never drains the error
                # while it blocks in recv), so HOLD the chunk and let
                # sync() replay it against the epoch it freezes.
                self._stash_presync(man, att)
                return b"ok", None
            if man.get("ep") != epoch:
                raise native.RpcError(
                    E_COLL_EPOCH,
                    f"collective epoch mismatch: chunk stamped "
                    f"{man.get('ep')}, member frozen at {epoch}")
            payload = att
            if payload is not None and not isinstance(payload, np.ndarray):
                payload = np.asarray(payload)
            try:
                pairs = list(groupwire.split_group(man, payload))
            except ValueError as ve:
                from brpc_tpu.runtime.tensor import E_UNDECODABLE

                raise native.RpcError(
                    E_UNDECODABLE, f"undecodable collective chunk: {ve}")
            key = (man["op"], int(man["seq"]), man["ph"],
                   int(man["step"]), int(man.get("frag", 0)))
            for entry, run in pairs:
                # Detach NOW: the attachment view dies with the handler.
                blob = (np.array(run) if run is not None
                        else np.empty(0, np.uint8))
                self._mailbox.deposit(key, (int(entry.get("idx", 0)),
                                            entry, blob))
            return b"ok", None
        raise native.RpcError(E_NO_SUCH, f"no such method: {method}")

    _PRESYNC_MAX = 256  # held chunks, bounded (oldest dropped)

    def _stash_presync(self, man: dict, att) -> None:
        payload = att
        if payload is not None and not isinstance(payload, np.ndarray):
            payload = np.asarray(payload)
        # Detach NOW: the attachment view dies with the handler.
        blob = np.array(payload) if payload is not None else None
        with self._mu:
            self._presync.append((man, blob))
            while len(self._presync) > self._PRESYNC_MAX:
                self._presync.pop(0)

    def _replay_presync(self, held: list) -> None:
        """Deposit held pre-sync chunks whose stamp matches the epoch
        sync() just froze; drop the rest (they keyed a ring this member
        never joined — deciding that is exactly what the hold deferred)."""
        for man, blob in held:
            if man.get("ep") != self._epoch:
                continue
            try:
                pairs = list(groupwire.split_group(man, blob))
            except ValueError:
                continue  # undecodable held chunk: op-level abort covers it
            key = (man["op"], int(man["seq"]), man["ph"],
                   int(man["step"]), int(man.get("frag", 0)))
            for entry, run in pairs:
                self._mailbox.deposit(
                    key, (int(entry.get("idx", 0)), entry,
                          run if run is not None else np.empty(0, np.uint8)))

    # ---- the collectives ----

    def _next_seq(self, name: str) -> int:
        with self._mu:
            s = self._seq.get(name, 0)
            self._seq[name] = s + 1
            return s

    def _pre_op(self, name: str):
        with self._mu:
            if self._closed:
                raise RuntimeError("collective group is closed")
            if self._epoch is None:
                raise RuntimeError("collective group not sync()ed")
            members = self._members
        if self._left.is_set():
            raise core.MemberLeft(
                f"member(s) left before op: {self.left_members} "
                "(re-sync() to rebuild the ring)")
        return members

    def allreduce(self, name: str, array, timeout_s: Optional[float] = None,
                  algo: str = "auto", on_chunk=None) -> np.ndarray:
        """Sum ``array`` across the frozen ring -> fp32 ndarray; every
        member returns identical values. ``algo``: ``"ring"``,
        ``"tree"``, or ``"auto"`` (tree at or below ``tree_max_bytes``).
        All members must call with the same ``name`` in the same order
        (the sequence number that pairs the ops derives from it).

        ``on_chunk(idx, (offset, length), values)`` — per-chunk finality
        trigger over the flattened array (:func:`core.ring_allreduce`'s
        T3 hook). Only the ring schedule has sub-array finality; the
        tree (and n==1) path fires the trigger ONCE with the whole span
        at completion, so callers get a uniform contract either way."""
        members = self._pre_op(name)
        n = len(members)
        host = np.ascontiguousarray(np.asarray(array), dtype=np.float32)
        if algo == "auto":
            algo = "tree" if host.nbytes <= self.tree_max_bytes else "ring"
        seq = self._next_seq(name)
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.op_timeout_s)
        if n == 1:
            codec_name = None
        elif algo == "tree":
            # Tree peers are NOT the ring successor: leaves send to the
            # root (negotiate with it), the root broadcasts ONE encode
            # to every leaf (quantize only if every leaf advertised the
            # codec — else that single encode would be undecodable at
            # the weakest member).
            root = members[ring_mod.tree_root(n)]
            if self.addr == root:
                codec_name = self._ring_codec(members)
            else:
                codec_name = self._codec_for(root)
        else:
            codec_name = self._ring_codec(members)
        link = _RpcLink(self, name, seq, deadline)
        t0 = time.monotonic()
        ok = False
        with tracing.trace_span("collective/allreduce"):
            tracing.annotate(f"op={name} seq={seq} algo={algo} n={n} "
                             f"bytes={host.nbytes}")
            try:
                if algo == "tree":
                    out = core.tree_allreduce(self.rank, n, host,
                                              self.chunk_codec, link,
                                              name, codec_name)
                    if on_chunk is not None and out.size:
                        on_chunk(0, (0, out.size),
                                 out.reshape(-1).copy())
                elif algo == "ring":
                    out = core.ring_allreduce(self.rank, n, host,
                                              self.chunk_codec, link,
                                              name, codec_name,
                                              frag_elems=self.frag_elems,
                                              on_chunk=on_chunk)
                else:
                    raise ValueError(f"unknown algo {algo!r}")
                ok = True
            finally:
                try:
                    link.close(ok)
                except native.RpcError as e:
                    raise self._map_rpc_error(e, "close", -1)
                finally:
                    self._mailbox.drop_op((name, seq))
                    if not ok:
                        self._m["aborts"].add(1)
                        tracing.annotate("aborted")
        self._m["allreduce"].record_s(time.monotonic() - t0)
        self._m["ops"].add(1)
        # Ring moves 2(n-1)/n logical chunks per member; count what THIS
        # member put on the wire vs the fp32 bytes it would have been.
        self._m["wire_bytes"].add(link.wire_bytes)
        self._m["logical_bytes"].add(
            int(host.nbytes * 2 * (n - 1) / n) if algo == "ring"
            else host.nbytes * (2 if self.rank == 0 else 1))
        return out.reshape(np.shape(host))

    def allgather(self, name: str, array,
                  timeout_s: Optional[float] = None) -> List[np.ndarray]:
        """Gather every member's ``array`` -> list indexed by rank (all
        members hold identical lists)."""
        members = self._pre_op(name)
        n = len(members)
        host = np.ascontiguousarray(np.asarray(array), dtype=np.float32)
        seq = self._next_seq(name)
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.op_timeout_s)
        codec_name = self._ring_codec(members) if n > 1 else None
        link = _RpcLink(self, name, seq, deadline)
        t0 = time.monotonic()
        ok = False
        with tracing.trace_span("collective/allgather"):
            tracing.annotate(f"op={name} seq={seq} n={n} "
                             f"bytes={host.nbytes}")
            try:
                out = core.ring_allgather(self.rank, n, host,
                                          self.chunk_codec, link, name,
                                          codec_name,
                                          frag_elems=self.frag_elems)
                ok = True
            finally:
                try:
                    link.close(ok)
                except native.RpcError as e:
                    raise self._map_rpc_error(e, "close", -1)
                finally:
                    self._mailbox.drop_op((name, seq))
                    if not ok:
                        self._m["aborts"].add(1)
        self._m["allgather"].record_s(time.monotonic() - t0)
        self._m["ops"].add(1)
        self._m["wire_bytes"].add(link.wire_bytes)
        self._m["logical_bytes"].add(int(host.nbytes * (n - 1)))
        return out

    def reduce_scatter(self, name: str, array,
                       timeout_s: Optional[float] = None):
        """Sum ``array`` across the ring, keeping only this member's
        owned chunk -> ``((offset, length), fp32 values)`` over the
        flattened input (every member derives the same span layout from
        ``ring.chunk_spans``). Half an allreduce's bytes; the verb for
        consumers that shard the reduced result anyway. Per-successor
        codec negotiation — each hop re-encodes."""
        members = self._pre_op(name)
        n = len(members)
        host = np.ascontiguousarray(np.asarray(array), dtype=np.float32)
        seq = self._next_seq(name)
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.op_timeout_s)
        codec_name = self._codec_for(members[(self.rank + 1) % n]) \
            if n > 1 else None
        link = _RpcLink(self, name, seq, deadline)
        t0 = time.monotonic()
        ok = False
        with tracing.trace_span("collective/reduce_scatter"):
            tracing.annotate(f"op={name} seq={seq} n={n} "
                             f"bytes={host.nbytes}")
            try:
                span, chunk = core.ring_reduce_scatter(
                    self.rank, n, host, self.chunk_codec, link, name,
                    codec_name, frag_elems=self.frag_elems)
                ok = True
            finally:
                try:
                    link.close(ok)
                except native.RpcError as e:
                    raise self._map_rpc_error(e, "close", -1)
                finally:
                    self._mailbox.drop_op((name, seq))
                    if not ok:
                        self._m["aborts"].add(1)
        self._m["reduce_scatter"].record_s(time.monotonic() - t0)
        self._m["ops"].add(1)
        self._m["wire_bytes"].add(link.wire_bytes)
        self._m["logical_bytes"].add(int(host.nbytes * (n - 1) / n))
        return span, chunk

    def broadcast(self, name: str, array=None, root: int = 0,
                  timeout_s: Optional[float] = None) -> np.ndarray:
        """One-to-all: rank ``root`` supplies ``array``; every member
        (root included, which adopts its own dequantized encode) returns
        the bitwise-identical fp32 result. The root quantizes only when
        EVERY member advertised the codec (one encode serves all — the
        tree-allreduce broadcast-leg rule)."""
        members = self._pre_op(name)
        n = len(members)
        host = None
        if array is not None:
            host = np.ascontiguousarray(np.asarray(array),
                                        dtype=np.float32)
        if self.rank == root and host is None:
            raise ValueError("broadcast root must supply the array")
        seq = self._next_seq(name)
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.op_timeout_s)
        codec_name = self._ring_codec(members) \
            if n > 1 and self.rank == root else None
        link = _RpcLink(self, name, seq, deadline)
        t0 = time.monotonic()
        ok = False
        with tracing.trace_span("collective/broadcast"):
            tracing.annotate(f"op={name} seq={seq} n={n} root={root}")
            try:
                out = core.tree_broadcast(self.rank, n, host,
                                          self.chunk_codec, link, name,
                                          codec_name, root=root,
                                          frag_elems=self.frag_elems)
                ok = True
            finally:
                try:
                    link.close(ok)
                except native.RpcError as e:
                    raise self._map_rpc_error(e, "close", -1)
                finally:
                    self._mailbox.drop_op((name, seq))
                    if not ok:
                        self._m["aborts"].add(1)
        self._m["broadcast"].record_s(time.monotonic() - t0)
        self._m["ops"].add(1)
        self._m["wire_bytes"].add(link.wire_bytes)
        if self.rank == root:
            self._m["logical_bytes"].add(int(host.nbytes * (n - 1)))
        return out

    # ---- lifecycle ----

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
            pool, self._chan_pool = self._chan_pool, {}
            if not self.left_members:
                self.left_members = ["<closed>"]
        # Fail concurrent ops NOW: a thread blocked in Mailbox.take must
        # not sit out its full op deadline waiting for chunks that can
        # never arrive once the server below stops. (Channels still
        # checked out by such an op close at their _checkin.)
        self._left.set()
        self._watcher.stop()
        self._reg.stop()
        for chans in pool.values():
            for ch in chans:
                try:
                    ch.close()
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass
        self.server.stop()

    def __enter__(self):
        return self

    def __exit__(self, *_exc):
        self.close()
