"""Per-chunk per-hop quantization for collectives — EQuARX's discipline
on the PR 7 codec.

EQuARX (PAPERS.md) puts the quantizer INSIDE the collective: every hop
dequantizes what arrived, reduces in full precision, and requantizes the
partial sum for the next hop with fresh per-block scales — so the wire
always moves ~1/4 the bytes while the arithmetic stays fp32. The hazard
is bias: each requantization rounds, and a naive requantizer's rounding
errors compound LINEARLY across hops and across repeated collectives
(every training step quantizes the same positions the same way).

The fix is the codec's error-feedback discipline stretched across
reduction steps: each (tensor, hop-role) position keeps a residual
accumulator — what the last quantization at this position dropped rides
along with the next collective's value at the same position, so the SUM
of what flows downstream tracks the fp32 reduction to within one quant
step, independent of how many collectives ran. ``ef=False`` is the
naive requantizer, kept as the pinned negative control.

Hop-role keys are stable by construction: under the ring schedule,
member ``r`` at reduce-scatter step ``s`` always handles chunk
``(r - s) % n``, so ``"<name>#rs<s>"`` names the same chunk position
every call; the single allgather quantization point is ``"<name>#ag"``,
and the tree's are ``"<name>#leaf"`` / ``"<name>#root"``.

Pure numpy + ``runtime.codec`` by contract — no native library, and jax
only as an OPTIONAL fast path: a collective quantizes every partial sum
fresh (nothing to cache, unlike the parameter server's
quantize-once-serve-many pulls), so the encoder sits on the hop's
critical path. The numpy int8 encoder walks ~5 memory passes; the
jitted XLA version fuses them (absmax -> scale -> round/clip/cast ->
dequantized residual source in one fused, multithreaded program,
measured ~4.6x faster on the 2-core CPU backend) and produces
BIT-IDENTICAL codes, so it auto-routes like ``fused_momentum_update``:
jax present -> fused, else numpy — the wire format cannot tell.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from brpc_tpu.runtime import codec as codec_mod

_fused = {"fn": None, "tried": False}


def _fused_int8():
    """The jitted encode(+dequantize) kernel, or None without jax.
    Padded to whole blocks so one compiled program serves every frag
    size of a given (padded) shape; zero padding is exact (an all-zero
    pad block quantizes to scale 0, codes 0, and real blocks never see
    pad bytes because the pad starts at a block boundary)."""
    if _fused["tried"]:
        return _fused["fn"]
    _fused["tried"] = True
    try:
        from functools import partial

        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("block",))
        def q8(xp, block):
            b = xp.reshape(-1, block)
            absmax = jnp.max(jnp.abs(b), axis=1)
            inv = jnp.where(absmax > 0,
                            np.float32(127.0) / absmax,
                            np.float32(0.0))
            q = jnp.clip(jnp.round(b * inv[:, None]),
                         -127.0, 127.0).astype(jnp.int8)
            scales = (absmax / np.float32(127.0)).astype(jnp.float32)
            # The error-feedback residual source, fused in: what this
            # quantization dropped (x - dequantized).
            res = (b - q.astype(jnp.float32) * scales[:, None]
                   ).reshape(-1)
            return q.reshape(-1), scales, res

        def run(flat: np.ndarray, block: int):
            n = flat.size
            pad = (-n) % block
            xp = (np.concatenate([flat, np.zeros(pad, np.float32)])
                  if pad else flat)
            q, scales, res = jax.block_until_ready(q8(xp, block))
            return (np.asarray(q)[:n], np.asarray(scales),
                    np.asarray(res)[:n])

        _fused["fn"] = run
    except Exception:  # noqa: BLE001 — no jax: numpy path serves
        _fused["fn"] = None
    return _fused["fn"]


class ChunkCodec:
    """Encode/decode one hop's chunk, raw or quantized-with-EF.

    ``encode(key, chunk, codec)`` -> ``(meta, blob_u8)``: the
    self-describing metadata entry (groupwire manifest keys) plus the
    wire bytes. ``codec=None`` — or an ineligible chunk (non-fp32, or
    below the size floor where scale overhead beats the savings) —
    rides raw, per chunk, transparently (the PR 7 degrade discipline:
    decode always follows the metadata that ARRIVED, never what was
    requested). Thread-safe: concurrent collectives on different lanes
    share the residual table under one lock."""

    def __init__(self, ef: bool = True, block: int = codec_mod.DEFAULT_BLOCK,
                 min_bytes: int = codec_mod.MIN_QUANT_BYTES):
        self.ef = ef
        self.block = block
        self.min_bytes = min_bytes
        self._mu = threading.Lock()
        self._efacc = codec_mod.ErrorFeedback()

    def encode(self, key: str, chunk: np.ndarray,
               codec: Optional[str]) -> Tuple[dict, np.ndarray]:
        flat = np.ascontiguousarray(chunk, dtype=np.float32).reshape(-1)
        if codec is not None and codec_mod.eligible(flat, self.min_bytes):
            fused = _fused_int8() if codec == "int8" else None
            if fused is not None:
                with self._mu:
                    x = (self._efacc.compensate(key, flat) if self.ef
                         else flat)
                    q, scales, res = fused(x, self.block)
                    if self.ef:
                        self._efacc.set_residual(key, res)
                wire = np.empty(scales.nbytes + q.nbytes, np.uint8)
                wire[:scales.nbytes] = scales.view(np.uint8)
                wire[scales.nbytes:] = q.view(np.uint8)
                meta = {"dtype": flat.dtype.str,
                        "shape": [int(flat.size)],
                        "codec": codec, "block": self.block}
                return meta, wire
            with self._mu:
                x = self._efacc.compensate(key, flat) if self.ef else flat
                # `codec` is the group's negotiated choice, fixed at
                # construction (group._codec_for); the quantizer
                # never picks one.  tpulint: allow(negotiation)
                enc = codec_mod.encode(x, codec, block=self.block,
                                       min_bytes=self.min_bytes)
                if enc is not None:
                    if self.ef:
                        self._efacc.settle(key, x, enc.dequantized()
                                           .reshape(-1))
                    meta = {"dtype": flat.dtype.str,
                            "shape": [int(flat.size)],
                            "codec": codec, "block": enc.block}
                    return meta, enc.wire
                # Encode declined after the eligibility pre-check
                # (defensive): fall through to raw — and drop any
                # residual, nothing was lost on a raw hop.
                self._efacc.clear(key)
        elif self.ef:
            # Raw hop: the exact bytes fly, so nothing is owed at this
            # position; a stale residual from an earlier quantized call
            # (codec renegotiated away) must not strand.
            with self._mu:
                self._efacc.clear(key)
        meta = {"dtype": flat.dtype.str, "shape": [int(flat.size)]}
        return meta, flat.view(np.uint8)

    def encode_chunk(self, key: str, chunk: np.ndarray,
                     codec: Optional[str],
                     frag_elems: int) -> list:
        """Encode one hop's whole chunk as its wire-fragment train ->
        ``[(meta, blob_u8), ...]`` in fragment order.

        When the fused int8 kernel is available and fragments fall on
        block boundaries (``frag_elems % block == 0`` — true for every
        default), the WHOLE chunk quantizes in ONE fused call (one jit
        dispatch, one EF position per hop) and the ``[scales][codes]``
        wire is sliced per fragment — each fragment still fully
        self-describing. Otherwise each fragment encodes independently
        (per-fragment EF keys ``<key>#f<i>`` — stable per call, so the
        feedback discipline holds either way)."""
        from brpc_tpu.collectives import ring as ring_mod

        flat = np.ascontiguousarray(chunk, dtype=np.float32).reshape(-1)
        fs = ring_mod.fragment_spans(flat.size, frag_elems)
        whole = (codec == "int8" and frag_elems % self.block == 0
                 and codec_mod.eligible(flat, self.min_bytes)
                 and _fused_int8() is not None)
        if not whole:
            return [self.encode(f"{key}#f{f}", flat[fo:fo + fl], codec)
                    for f, (fo, fl) in enumerate(fs)]
        fused = _fused_int8()
        with self._mu:
            x = self._efacc.compensate(key, flat) if self.ef else flat
            q, scales, res = fused(x, self.block)
            if self.ef:
                self._efacc.set_residual(key, res)
        out = []
        block = self.block
        for fo, fl in fs:
            b0 = fo // block
            nb = -(-fl // block) if fl else 0
            s_f = scales[b0:b0 + nb]
            q_f = q[fo:fo + fl]
            wire = np.empty(s_f.nbytes + q_f.nbytes, np.uint8)
            wire[:s_f.nbytes] = s_f.view(np.uint8)
            wire[s_f.nbytes:] = q_f.view(np.uint8)
            out.append(({"dtype": flat.dtype.str, "shape": [int(fl)],
                         "codec": codec, "block": block}, wire))
        return out

    def decode(self, meta: dict, blob) -> np.ndarray:
        """Received metadata + bytes -> fresh fp32 1-D array (never
        aliases the input view — decoding IS the detach)."""
        buf = np.asarray(blob).reshape(-1).view(np.uint8)
        if "codec" in meta:
            return codec_mod.decode(meta, buf).reshape(-1)
        out = np.array(np.frombuffer(buf, dtype=np.dtype(meta["dtype"])),
                       dtype=np.float32)
        return out

    def reduce_into(self, meta: dict, blob, out: np.ndarray) -> None:
        """``out += decode(meta, blob)`` without the intermediate copy
        on the raw path (the reduce-scatter hot loop adds straight from
        the received bytes; quantized payloads still materialize the
        dequantized temp — that pass IS the dequant)."""
        buf = np.asarray(blob).reshape(-1).view(np.uint8)
        if "codec" in meta:
            out += codec_mod.decode(meta, buf).reshape(-1)
        else:
            out += np.frombuffer(buf, dtype=np.dtype(meta["dtype"]))

    def prune(self, keep) -> int:
        """Drop residuals whose key fails ``keep(key)`` — the reshard
        hook: a ring rebuild after membership change shifts every hop
        role, and stale full-chunk fp32 residuals would otherwise strand
        for the codec's lifetime."""
        with self._mu:
            return self._efacc.prune(keep)

    def residual(self, key: str) -> Optional[np.ndarray]:
        with self._mu:
            return self._efacc.residual(key)
