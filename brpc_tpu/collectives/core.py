"""Transport-agnostic collective algorithms — the tier-1-pure engine.

The ring/tree schedules (``ring.py``) plus the per-hop codec
(``quant.py``) compose into allreduce/allgather here against an abstract
``Link``; :class:`~brpc_tpu.collectives.group.CollectiveGroup` wires a
Link to the real tensor wire (per-peer TensorChannel + PipelineWindow),
while the pure units wire one to in-memory queues — same algorithm
object code on both, so the tier-1 units really do pin what the fleet
runs.

Link protocol (duck-typed):

  * ``send(dst_rank, phase, step, idx, meta, blob, frag=0, nfrags=1)``
    — deliver one chunk fragment; may buffer/pipeline, must raise on a
    dead peer.
  * ``recv(phase, step, frag=0)`` -> ``(idx, meta, blob)`` — block for
    the matching inbound fragment; raises :class:`CollectiveAborted`
    flavors on timeout/abort (a member left, the deadline passed).

Hops are FRAGMENTED (``ring.fragment_spans``): each chunk rides as a
train of bounded fragments so the sender's encode/stage of fragment f+1
overlaps the wire of fragment f and the receiver reduces fragments as
they arrive — without this, an 8MB hop is one monolithic RPC whose
staging, wire and decode serialize (measured ~2x slower end to end).

Failure semantics (the PartialPush/PartialPull pattern one level up): a
hop failure raises :class:`CollectiveAborted` carrying ``done`` — the
chunk indexes whose FINAL reduced value this member already holds, with
their spans and values — so a caller can salvage partial results (or
verify nothing landed) instead of guessing. The operation never
half-applies: the input array is not mutated.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from brpc_tpu.collectives import ring as ring_mod
from brpc_tpu.collectives.quant import ChunkCodec

# App-level error codes, continuing the 2040+ range (param_server.py
# holds 2040-2043, tensor.py E_UNDECODABLE=2044).
E_COLL_EPOCH = 2045   # chunk stamped with a different membership epoch
E_COLL_ABORT = 2046   # collective failed (timeout / member left)


class CollectiveAborted(RuntimeError):
    """A collective failed cleanly mid-flight.

    ``phase``/``step`` locate the hop; ``done`` maps chunk index ->
    ``(span, fp32 values)`` for every chunk whose FINAL reduction this
    member already completed (per-chunk salvage); ``reason`` is the
    triggering condition ("timeout", "member-left", "epoch", or the
    transport error text)."""

    def __init__(self, reason: str, phase: str = "", step: int = -1,
                 done: Optional[Dict[int, tuple]] = None):
        at = f" at {phase}:{step}" if phase else ""
        salv = f"; {len(done or {})} chunk(s) salvaged"
        super().__init__(f"collective aborted{at}: {reason}{salv}")
        self.reason = reason
        self.phase = phase
        self.step = step
        self.done = dict(done or {})


class MemberLeft(CollectiveAborted):
    """Registry watch (or a dead-peer transport error) reported a frozen
    ring member gone mid-collective."""


class CollectiveTimeout(CollectiveAborted):
    """The op deadline elapsed waiting for a hop."""


def _salvage(acc: np.ndarray, spans, done_idx) -> Dict[int, tuple]:
    return {i: (spans[i], acc[spans[i][0]:spans[i][0] + spans[i][1]].copy())
            for i in sorted(done_idx)}


DEFAULT_FRAG_ELEMS = 1 << 18  # 1MB of fp32 per wire fragment


def _detach_u8(blob) -> np.ndarray:
    """A forwarding copy that cannot alias transport-owned pages."""
    return np.array(np.asarray(blob).reshape(-1).view(np.uint8))


def ring_allreduce(rank: int, n: int, x: np.ndarray, codec: ChunkCodec,
                   link, name: str, codec_name=None,
                   frag_elems: int = DEFAULT_FRAG_ELEMS,
                   on_chunk=None) -> np.ndarray:
    """Sum ``x`` across the ring -> fp32 array shaped like ``x``;
    every member returns the IDENTICAL values (the owner of a chunk
    adopts the dequantized form it broadcast, so quantization cannot
    make members disagree).

    ``on_chunk(idx, (offset, length), values)`` — the T3 track-and-
    trigger hook (ISSUE 20, arXiv 2401.16677): fires on the CALLER's
    thread the moment chunk ``idx`` reaches its FINAL value, while later
    chunks are still on the wire. Finality points: the owned chunk fires
    inside allgather step 0 AFTER the dequantized adoption (firing right
    after reduce-scatter would hand the trigger a value quantization is
    about to replace — members would disagree); every other chunk fires
    as its allgather hop decodes. ``values`` is a detached fp32 copy of
    the final span; a trigger exception aborts the op like any link
    failure. The raw SUM is what lands — averaging is the trigger's job,
    exactly as it is the caller's on the returned array."""
    flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    if n == 1:
        out = flat.copy()
        if on_chunk is not None and out.size:
            on_chunk(0, (0, out.size), out.copy())
        return out.reshape(np.shape(x))
    acc = flat.copy()
    spans = ring_mod.chunk_spans(acc.size, n)
    succ = (rank + 1) % n
    done: set = set()
    # ---- reduce-scatter: n-1 hops, each dequant -> add -> (next hop
    # requantizes with its own EF position). Fragmented: send the whole
    # fragment train first (the window pipelines staging against the
    # wire), then reduce inbound fragments as they land.
    for s, (send_idx, recv_idx) in enumerate(
            ring_mod.reduce_scatter_steps(rank, n)):
        off, ln = spans[send_idx]
        try:
            frags = codec.encode_chunk(f"{name}#rs{s}",
                                       acc[off:off + ln], codec_name,
                                       frag_elems)
            for f, (meta, blob) in enumerate(frags):
                link.send(succ, "rs", s, send_idx, meta, blob,
                          frag=f, nfrags=len(frags))
            roff, rln = spans[recv_idx]
            for f, (fo, fl) in enumerate(
                    ring_mod.fragment_spans(rln, frag_elems)):
                _idx, rmeta, rblob = link.recv("rs", s, frag=f)
                if fl:
                    codec.reduce_into(rmeta, rblob,
                                      acc[roff + fo:roff + fo + fl])
        except CollectiveAborted as e:
            e.done = _salvage(acc, spans, done)
            raise
    own = ring_mod.owned_chunk(rank, n)
    done.add(own)
    # ---- allgather: the owner quantizes its reduced chunk ONCE (and
    # adopts the dequantized value so all members agree); every later
    # hop forwards the received fragments VERBATIM — no requant, no
    # compounding ----
    fwd: Optional[list] = None  # [(meta, detached blob), ...] per frag
    for s, (send_idx, recv_idx) in enumerate(
            ring_mod.allgather_steps(rank, n)):
        try:
            if s == 0:
                ooff, oln = spans[own]
                send_frags = codec.encode_chunk(f"{name}#ag",
                                                acc[ooff:ooff + oln],
                                                codec_name, frag_elems)
                for (meta, blob), (fo, fl) in zip(
                        send_frags,
                        ring_mod.fragment_spans(oln, frag_elems)):
                    if fl:
                        acc[ooff + fo:ooff + fo + fl] = codec.decode(
                            meta, blob)
                if on_chunk is not None and oln:
                    on_chunk(own, (ooff, oln),
                             acc[ooff:ooff + oln].copy())
            else:
                send_frags = fwd  # type: ignore[assignment]
            for f, (meta, blob) in enumerate(send_frags):
                link.send(succ, "ag", s, send_idx, meta, blob,
                          frag=f, nfrags=len(send_frags))
            roff, rln = spans[recv_idx]
            fwd = []
            for f, (fo, fl) in enumerate(
                    ring_mod.fragment_spans(rln, frag_elems)):
                _idx, rmeta, rblob = link.recv("ag", s, frag=f)
                if fl:
                    acc[roff + fo:roff + fo + fl] = codec.decode(rmeta,
                                                                 rblob)
                fwd.append((rmeta, _detach_u8(rblob)))
        except CollectiveAborted as e:
            e.done = _salvage(acc, spans, done)
            raise
        done.add(recv_idx)
        if on_chunk is not None and rln:
            on_chunk(recv_idx, (roff, rln), acc[roff:roff + rln].copy())
    return acc.reshape(np.shape(x))


def ring_reduce_scatter(rank: int, n: int, x: np.ndarray,
                        codec: ChunkCodec, link, name: str, codec_name=None,
                        frag_elems: int = DEFAULT_FRAG_ELEMS):
    """The ring's reduce-scatter phase as a standalone verb: sum ``x``
    across the ring, each member keeping ONLY its owned chunk — the
    bandwidth-optimal building block (S(n-1)/n bytes per member, half an
    allreduce) for workloads that shard the reduced result anyway (a
    sharded optimizer step; an allgather later completes an allreduce).
    Returns ``((offset, length), fp32 chunk values)`` over the flattened
    input — ``offset/length`` = ``chunk_spans(x.size, n)[owned_chunk]``,
    identical on every member's derivation.

    Each hop re-encodes (dequant -> add -> requant with the hop's own EF
    position, exactly the allreduce rs phase), so the codec negotiates
    per SUCCESSOR — no ring-wide agreement needed (unlike allgather
    forwarding)."""
    flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    spans = ring_mod.chunk_spans(flat.size, n)
    own = ring_mod.owned_chunk(rank, n)
    if n == 1:
        return spans[0], flat.copy()
    acc = flat.copy()
    succ = (rank + 1) % n
    for s, (send_idx, recv_idx) in enumerate(
            ring_mod.reduce_scatter_steps(rank, n)):
        off, ln = spans[send_idx]
        try:
            frags = codec.encode_chunk(f"{name}#rs{s}",
                                       acc[off:off + ln], codec_name,
                                       frag_elems)
            for f, (meta, blob) in enumerate(frags):
                link.send(succ, "rs", s, send_idx, meta, blob,
                          frag=f, nfrags=len(frags))
            roff, rln = spans[recv_idx]
            for f, (fo, fl) in enumerate(
                    ring_mod.fragment_spans(rln, frag_elems)):
                _idx, rmeta, rblob = link.recv("rs", s, frag=f)
                if fl:
                    codec.reduce_into(rmeta, rblob,
                                      acc[roff + fo:roff + fo + fl])
        except CollectiveAborted as e:
            e.done = {}  # no chunk is final until the last hop lands
            raise
    off, ln = spans[own]
    return (off, ln), acc[off:off + ln].copy()


def tree_broadcast(rank: int, n: int, x, codec: ChunkCodec, link,
                   name: str, codec_name=None, root: int = 0,
                   frag_elems: int = DEFAULT_FRAG_ELEMS) -> np.ndarray:
    """One-to-all broadcast on the tree schedule: the root encodes ONCE
    (quantized only when every receiver can decode it — the tree-
    allreduce broadcast-leg rule) and sends to every other member; the
    root ADOPTS its own dequantized form so all members return bitwise
    identical arrays. Non-root members pass ``x=None`` — fragment 0's
    metadata carries the shape (the allgather framing), which is all a
    receiver needs."""
    if n == 1:
        return np.ascontiguousarray(x, dtype=np.float32).copy()
    if rank != root:
        _idx, rmeta0, rblob0 = link.recv("bc", 0, frag=0)
        nfrags = int(rmeta0.get("nfrags", 1))
        parts = [codec.decode(rmeta0, rblob0)]
        for f in range(1, nfrags):
            _idx, rmeta, rblob = link.recv("bc", 0, frag=f)
            parts.append(codec.decode(rmeta, rblob))
        return np.concatenate(parts).reshape(rmeta0.get("oshape", [-1]))
    if x is None:
        raise ValueError("broadcast root must supply the array")
    flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    shape = list(np.shape(x))
    frags = codec.encode_chunk(f"{name}#bc", flat, codec_name, frag_elems)
    frags[0] = (dict(frags[0][0], oshape=shape, src=root,
                     nfrags=len(frags)), frags[0][1])
    for dst in range(n):
        if dst == root:
            continue
        for f, (meta, blob) in enumerate(frags):
            link.send(dst, "bc", 0, root, meta, blob,
                      frag=f, nfrags=len(frags))
    parts = [codec.decode(meta, blob) for meta, blob in frags]
    out = np.concatenate(parts) if parts else flat.copy()
    return out.reshape(shape)


def tree_allreduce(rank: int, n: int, x: np.ndarray, codec: ChunkCodec,
                   link, name: str, codec_name=None) -> np.ndarray:
    """The small-tensor latency play: leaves send to the root, the root
    reduces (ascending rank order — deterministic) and broadcasts. Two
    hops end to end at any n; one quantization per leg."""
    flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    if n == 1:
        return flat.copy().reshape(np.shape(x))
    root = ring_mod.tree_root(n)
    if rank != root:
        # codec_name is the caller's per-peer negotiated pick
        # (group._codec_for); this free function never chooses a
        # codec itself.  tpulint: allow(negotiation)
        meta, blob = codec.encode(f"{name}#leaf", flat, codec_name)
        link.send(root, "tr", rank, 0, meta, blob)
        _idx, rmeta, rblob = link.recv("trb", 0)
        return codec.decode(rmeta, rblob).reshape(np.shape(x))
    acc = flat.copy()
    for src in ring_mod.tree_gather_srcs(n):
        _idx, rmeta, rblob = link.recv("tr", src)
        acc += codec.decode(rmeta, rblob)
    # Same as the leaf leg: the root echoes the caller-negotiated
    # codec_name.  tpulint: allow(negotiation)
    meta, blob = codec.encode(f"{name}#root", acc, codec_name)
    for dst in ring_mod.tree_gather_srcs(n):
        link.send(dst, "trb", 0, 0, meta, blob)
    # Adopt the broadcast form: members must agree bit-for-bit.
    return codec.decode(meta, blob).reshape(np.shape(x))


def ring_allgather(rank: int, n: int, x: np.ndarray, codec: ChunkCodec,
                   link, name: str, codec_name=None,
                   frag_elems: int = DEFAULT_FRAG_ELEMS
                   ) -> List[np.ndarray]:
    """Gather every member's ``x`` -> list indexed by rank. Each
    contribution is quantized ONCE at its source and forwarded verbatim
    (pure data movement — re-quantizing a forward would add error for
    nothing); the contributor adopts its own dequantized form so all
    members hold identical lists. Contributions may differ in shape:
    fragment 0's metadata carries the sender's shape and fragment count
    (``oshape``/``src``/``nfrags``), which is all a receiver needs."""
    if n == 1:
        return [np.ascontiguousarray(x, dtype=np.float32)]
    flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    succ = (rank + 1) % n
    shape = list(np.shape(x))
    out: List[Optional[np.ndarray]] = [None] * n
    own_frags = codec.encode_chunk(f"{name}#ag", flat, codec_name,
                                   frag_elems)
    own_frags[0] = (dict(own_frags[0][0], oshape=shape, src=rank,
                         nfrags=len(own_frags)), own_frags[0][1])
    own_parts = [codec.decode(meta, blob) for meta, blob in own_frags]
    out[rank] = np.concatenate(own_parts).reshape(shape) if own_parts \
        else np.zeros(shape, np.float32)
    done = {rank}
    fwd = own_frags
    for s in range(n - 1):
        try:
            for f, (meta, blob) in enumerate(fwd):
                link.send(succ, "ag", s, int(fwd[0][0]["src"]), meta,
                          blob, frag=f, nfrags=len(fwd))
            _idx, rmeta0, rblob0 = link.recv("ag", s, frag=0)
            nfrags = int(rmeta0.get("nfrags", 1))
            parts = [codec.decode(rmeta0, rblob0)]
            nxt = [(rmeta0, _detach_u8(rblob0))]
            for f in range(1, nfrags):
                _idx, rmeta, rblob = link.recv("ag", s, frag=f)
                parts.append(codec.decode(rmeta, rblob))
                nxt.append((rmeta, _detach_u8(rblob)))
        except CollectiveAborted as e:
            e.done = {i: ((0, 0), out[i]) for i in sorted(done)}
            raise
        src = int(rmeta0["src"])
        out[src] = np.concatenate(parts).reshape(
            rmeta0.get("oshape", [-1]))
        done.add(src)
        fwd = nxt
    return out  # type: ignore[return-value]


class Mailbox:
    """Keyed rendezvous between the transport's deposit side (RPC
    handlers / queue feeders) and the algorithm's ``recv`` — one slot
    per ``(op, seq, phase, step)``, idempotent deposit (a paced retry
    redelivers the same bytes), abortable waits."""

    _TOMBSTONES = 256  # dropped-op prefixes remembered (bounded)

    def __init__(self):
        self._mu = threading.Lock()
        self._cond = threading.Condition(self._mu)
        self._slots: Dict[tuple, tuple] = {}
        # Tombstones for dropped ops: a peer's in-flight chunk can land
        # AFTER the op aborted and drop_op() ran — without this, that
        # late deposit (op seqs never reuse, so nobody will take it)
        # strands its detached copy in the mailbox for the transport's
        # lifetime, one chunk per abort.
        self._dropped: "OrderedDict[tuple, None]" = OrderedDict()

    def deposit(self, key: tuple, value: tuple) -> None:
        with self._mu:
            for n in range(1, len(key)):
                if key[:n] in self._dropped:
                    return  # late chunk for an aborted/finished op
            self._slots[key] = value  # idempotent: retries overwrite
            self._cond.notify_all()

    def take(self, key: tuple, deadline: float,
             abort_event: Optional[threading.Event] = None,
             now=None) -> tuple:
        """Wait for ``key`` until monotonic ``deadline``; raises
        :class:`MemberLeft` when ``abort_event`` fires first,
        :class:`CollectiveTimeout` at the deadline."""
        import time as _time
        clock = now if now is not None else _time.monotonic
        with self._mu:
            while True:
                v = self._slots.pop(key, None)
                if v is not None:
                    return v
                if abort_event is not None and abort_event.is_set():
                    raise MemberLeft("member-left", key[2], key[3])
                remaining = deadline - clock()
                if remaining <= 0:
                    raise CollectiveTimeout("timeout", key[2], key[3])
                # Bounded waits so an abort_event set between checks is
                # seen promptly (the event is set by a watcher thread
                # that cannot reach this condition variable).
                self._cond.wait(min(remaining, 0.05))

    def drop_op(self, op_prefix: tuple) -> int:
        """GC every slot whose key starts with ``op_prefix`` and
        tombstone the prefix — an aborted op must not strand chunks
        that are ALREADY here, and ones still in flight must be
        discarded on arrival (op seqs never reuse, so a tombstone can
        never swallow a live op's chunk)."""
        with self._mu:
            dead = [k for k in self._slots
                    if k[:len(op_prefix)] == op_prefix]
            for k in dead:
                self._slots.pop(k, None)
            self._dropped[op_prefix] = None
            while len(self._dropped) > self._TOMBSTONES:
                self._dropped.popitem(last=False)
            return len(dead)
