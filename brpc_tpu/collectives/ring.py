"""Ring & tree collective schedules — the pure topology math.

The chunk-schedule discipline follows "Memory-efficient array
redistribution" (PAPERS.md): a collective over an S-byte tensor never
materializes more than one chunk per peer in flight — the tensor splits
into ``n`` near-equal contiguous spans and every hop moves exactly one
span, so peak extra memory is O(S/n) per member and the wire pipeline
(PipelineWindow one level down) stays busy with bounded staging.

Ring allreduce is the classic two-phase schedule (reduce-scatter then
allgather, 2(n-1) hops moving 2S(n-1)/n bytes per member — bandwidth-
optimal); the tree schedule is the latency play for SMALL tensors where
2(n-1) serialized hops of a few KB are all fixed cost: leaves send to
the root, the root reduces and broadcasts (2 hops at any n).

Everything here is pure arithmetic on ``(rank, n)`` — no numpy, no
native, no transport — so the tier-1 units can pin the schedules
exhaustively.
"""

from __future__ import annotations

from typing import List, Tuple


def ring_order(members) -> List[str]:
    """The ring: members sorted — every participant derives the SAME
    order from the registry's membership list with no coordination (the
    ShardMap discipline: the list + a deterministic rule IS the map)."""
    return sorted(set(members))


def chunk_spans(n_elems: int, parts: int) -> List[Tuple[int, int]]:
    """Split ``n_elems`` into ``parts`` contiguous ``(offset, length)``
    spans, sizes differing by at most one (the first ``n % parts`` spans
    take the extra element). Zero-length spans are legal — a tensor
    smaller than the ring still reduces correctly, the empty hops just
    carry empty payloads."""
    if parts < 1:
        raise ValueError(f"parts must be >= 1, got {parts}")
    base, extra = divmod(n_elems, parts)
    spans, off = [], 0
    for i in range(parts):
        ln = base + (1 if i < extra else 0)
        spans.append((off, ln))
        off += ln
    return spans


def fragment_spans(n_elems: int, frag_elems: int) -> List[Tuple[int, int]]:
    """Split one hop's chunk into wire fragments of at most
    ``frag_elems`` elements — the PipelineWindow-level chunking: the
    sender stages/encodes fragment f+1 while fragment f flies, the
    receiver reduces fragments as they land, and peak staging stays
    O(window x frag) instead of O(chunk) (the array-redistribution
    memory discipline). Every member derives the SAME fragmentation
    from the globally-known span length, so no count needs negotiating.
    A zero-length chunk is one empty fragment (the lockstep must not
    skip a message slot)."""
    if frag_elems < 1:
        raise ValueError(f"frag_elems must be >= 1, got {frag_elems}")
    if n_elems == 0:
        return [(0, 0)]
    out, off = [], 0
    while off < n_elems:
        ln = min(frag_elems, n_elems - off)
        out.append((off, ln))
        off += ln
    return out


def reduce_scatter_steps(rank: int, n: int) -> List[Tuple[int, int]]:
    """The n-1 reduce-scatter hops for ``rank``: step ``s`` sends chunk
    ``(rank - s) % n`` to the successor and receives chunk
    ``(rank - s - 1) % n`` from the predecessor (added into the local
    accumulator). After the last step, ``rank`` holds the fully reduced
    chunk ``owned_chunk(rank, n)``."""
    return [((rank - s) % n, (rank - s - 1) % n) for s in range(n - 1)]


def owned_chunk(rank: int, n: int) -> int:
    """The chunk whose reduction completes at ``rank``."""
    return (rank + 1) % n


def allgather_steps(rank: int, n: int) -> List[Tuple[int, int]]:
    """The n-1 allgather hops: step ``s`` sends chunk
    ``(rank + 1 - s) % n`` (the owned chunk first, then each chunk as it
    arrives — a pure forward, no recompute) and receives chunk
    ``(rank - s) % n``."""
    return [((rank + 1 - s) % n, (rank - s) % n) for s in range(n - 1)]


def reduce_order(chunk_idx: int, n: int) -> List[int]:
    """The rank order in which contributions accumulate into chunk
    ``chunk_idx`` under the ring schedule — ``[chunk_idx, chunk_idx+1,
    ... mod n]``. This makes the raw (fp32) ring reduction BIT-exact
    reproducible: summing members' chunks left-to-right in this order
    yields the identical float result, the reference the byte-identity
    tests (and any debugging of a quantized drift) compare against."""
    return [(chunk_idx + i) % n for i in range(n)]


def tree_root(n: int) -> int:
    return 0


def tree_gather_srcs(n: int) -> List[int]:
    """The rank order the root reduces leaf contributions in
    (deterministic: ascending rank — the reference order)."""
    return list(range(1, n))
