"""Fleet collectives: ring/tree allreduce + allgather over the tensor
wire (ISSUE 13).

Layering (pure -> wire):

  * :mod:`~brpc_tpu.collectives.ring` — chunk spans and hop schedules,
    pure arithmetic;
  * :mod:`~brpc_tpu.collectives.quant` — per-chunk per-hop quantization
    with cross-step error feedback (EQuARX's dequant/reduce/requant);
  * :mod:`~brpc_tpu.collectives.core` — transport-agnostic algorithms +
    mailbox + the per-chunk-salvage failure contract;
  * :mod:`~brpc_tpu.collectives.group` — :class:`CollectiveGroup`, the
    registry-membered, per-peer-channeled, QoS/overload/trace-integrated
    real thing.
"""

from brpc_tpu.collectives.core import (CollectiveAborted,  # noqa: F401
                                       CollectiveTimeout, E_COLL_ABORT,
                                       E_COLL_EPOCH, Mailbox, MemberLeft,
                                       ring_allgather, ring_allreduce,
                                       ring_reduce_scatter, tree_allreduce,
                                       tree_broadcast)
from brpc_tpu.collectives.quant import ChunkCodec  # noqa: F401
from brpc_tpu.collectives.ring import (allgather_steps,  # noqa: F401
                                       chunk_spans, owned_chunk,
                                       reduce_order, reduce_scatter_steps,
                                       ring_order)

__all__ = [
    "CollectiveAborted", "CollectiveTimeout", "MemberLeft", "Mailbox",
    "ChunkCodec", "CollectiveGroup", "collective_metrics",
    "E_COLL_ABORT", "E_COLL_EPOCH",
    "ring_allreduce", "ring_allgather", "ring_reduce_scatter",
    "tree_allreduce", "tree_broadcast",
    "chunk_spans", "ring_order", "owned_chunk", "reduce_order",
    "reduce_scatter_steps", "allgather_steps",
]


def __getattr__(name):
    # CollectiveGroup pulls in the RPC stack (param_server -> jax);
    # lazy-load it so the pure schedule/codec layers import with nothing
    # but numpy (the tier-1-unit contract).
    if name in ("CollectiveGroup", "collective_metrics"):
        from brpc_tpu.collectives import group as _g

        return getattr(_g, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
